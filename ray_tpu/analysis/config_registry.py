"""The authoritative ``RTPU_*`` configuration registry.

Every environment variable the runtime reads is declared here —
rtpulint RTPU005 fails on any ``RTPU_*`` read missing from this table
(with near-miss typo detection), and the round-trip test fails on any
entry the tree no longer reads, so the registry can't drift in either
direction. ``python -m ray_tpu.analysis --gen-docs`` renders it into
docs/CONFIGURATION.md.

Two sources compose :data:`CONFIG_VARS`:

* :data:`STATIC_VARS` — variables read directly by name somewhere in
  ``ray_tpu/`` (or by the test harness, subsystem ``testing``).
* the ``SystemConfig`` dataclass (``ray_tpu/common/config.py``), whose
  every field is overridable as ``RTPU_<FIELD_UPPER>`` via
  ``apply_env_overrides()`` — those names are derived programmatically
  so a new config field is registered the moment it's declared.

Entry shape: ``{"subsystem": str, "default": str, "description": str}``.
"""

from __future__ import annotations

from dataclasses import fields as _dc_fields
from typing import Dict

__all__ = ["CONFIG_VARS", "STATIC_VARS", "system_config_vars"]


def _e(subsystem: str, default: str, description: str) -> Dict[str, str]:
    return {"subsystem": subsystem, "default": default,
            "description": description}


STATIC_VARS: Dict[str, Dict[str, str]] = {
    # ---------------------------------------------------- bootstrap/core
    "RTPU_ADDRESS": _e(
        "core", "(unset)",
        "GCS address to connect to (`ray_tpu.init()` default, the CLI, "
        "job submission and the autoscaler all honor it)."),
    "RTPU_SESSION_DIR": _e(
        "core", "(per-session tmpdir)",
        "Per-session scratch directory: sockets, logs, object-store "
        "files, profiles."),
    "RTPU_GCS_ADDRESS": _e(
        "core", "(set by node launcher)",
        "GCS endpoint handed to spawned raylets/workers."),
    "RTPU_GCS_PORT": _e(
        "core", "0 (auto)",
        "Listen port for a standalone GCS process (`gcs_main`)."),
    "RTPU_GCS_STORE_DIR": _e(
        "core", "(unset = in-memory)",
        "Directory for the GCS persistent store (journal survives a "
        "GCS restart)."),
    "RTPU_RAYLET_ADDRESS": _e(
        "core", "(set by raylet)",
        "Raylet RPC address injected into spawned workers."),
    "RTPU_STORE_PATH": _e(
        "core", "(set by raylet)",
        "Plasma object-store socket path injected into workers."),
    "RTPU_NODE_ID": _e(
        "core", "(generated)",
        "Node id of the hosting raylet (workers, tracing and the TPU "
        "profiler tag records with it)."),
    "RTPU_WORKER_ID": _e(
        "core", "(generated)",
        "Worker id assigned by the spawning raylet."),
    "RTPU_IS_HEAD": _e(
        "core", "(set by launcher)",
        "Marks the raylet as the head node (hosts GCS-adjacent "
        "services; chaos `head` filters key off it)."),
    "RTPU_LABELS": _e(
        "core", "{}",
        "JSON dict of node labels for label-aware scheduling."),
    "RTPU_RESOURCES": _e(
        "core", "{}",
        "JSON dict of custom resources the raylet registers."),
    "RTPU_NUM_TPUS": _e(
        "core", "(autodetect)",
        "Overrides the TPU chip count the raylet advertises."),
    "RTPU_OBJECT_STORE_BYTES": _e(
        "core", "(SystemConfig default)",
        "Object-store capacity for a launched raylet."),
    "RTPU_SYSTEM_CONFIG": _e(
        "core", "(unset)",
        "JSON `SystemConfig` overrides distributed cluster-wide from "
        "the head (see the SystemConfig table below for per-field "
        "`RTPU_*` overrides)."),
    "RTPU_LOG_LEVEL": _e(
        "core", "INFO (WARNING in workers)",
        "Python logging level for GCS/raylet/worker processes."),
    "RTPU_SAVED_AXON_POOL_IPS": _e(
        "core", "(internal)",
        "Stash for `PALLAS_AXON_POOL_IPS` while node launchers defer "
        "TPU-plugin env to child processes; restored by "
        "raylet_main/gcs_main."),
    "RTPU_JOB_ID": _e(
        "core", "(generated)",
        "Job id tag propagated to tasks submitted through the job "
        "API."),
    # --------------------------------------------------------- wire/rpc
    "RTPU_NATIVE_RPC": _e(
        "rpc", "1",
        "Use the native epoll frame pump + worker direct-execution "
        "lane (schema 1.7); 0 falls back to the asyncio wire."),
    "RTPU_NATIVE_SCHED": _e(
        "rpc", "1",
        "Use the native scheduling core (schedcore); 0 = pure-Python "
        "scheduler."),
    "RTPU_LOOP_STALL_S": _e(
        "rpc", "0 (off)",
        "Event-loop stall detector threshold in seconds: a loop "
        "blocked longer logs a stall with the offending stack."),
    "RTPU_VALIDATE_WIRE": _e(
        "rpc", "0",
        "Validate every inbound RPC payload against the schema table "
        "(tests enable it so schema drift fails immediately)."),
    # ------------------------------------------------------------- netx
    "RTPU_NETX": _e(
        "netx", "1",
        "Enable the cross-node transport plane (schema 1.8): TCP "
        "endpoint advertisement, direct-lane actor/task calls off-box, "
        "and chunk-pipelined object pulls; 0 keeps everything on the "
        "unix/asyncio paths."),
    "RTPU_NODE_IP": _e(
        "netx", "(resolved hostname)",
        "IP this node advertises for its TCP endpoints (raylet, direct "
        "lane, dag channels). Falls back to the resolved non-loopback "
        "hostname, then 127.0.0.1."),
    "RTPU_NET_FORCE_TCP": _e(
        "netx", "0",
        "Prefer host:port endpoints even for same-host peers (the "
        "multi-\"host\" test harness uses it to exercise the TCP lanes "
        "on one machine)."),
    "RTPU_NET_KEEPALIVE_S": _e(
        "netx", "10",
        "Quiet-connection ping interval for the netx pool; a peer "
        "missing ~3 windows is declared dead and redialed with "
        "backoff."),
    "RTPU_NET_IDLE_S": _e(
        "netx", "60",
        "Idle cross-node connections are reaped from the netx pool "
        "after this many seconds."),
    "RTPU_NET_RECONNECT_S": _e(
        "netx", "0.2",
        "Initial redial backoff after a netx connection failure; "
        "doubles per failure up to a 5 s cap."),
    "RTPU_NET_POOL_MAX": _e(
        "netx", "16",
        "Soft cap on pooled netx connections per process; LRU-idle "
        "peers are evicted beyond it."),
    "RTPU_NET_STALL_S": _e(
        "netx", "10",
        "A chunked object pull making no progress for this long is "
        "cancelled and resumed from its contiguous high-water mark "
        "(fresh connection, remaining attempts)."),
    # ------------------------------------------------------- observability
    "RTPU_CPROFILE_DIR": _e(
        "observability", "(unset = off)",
        "Write per-process cProfile dumps here on exit."),
    "RTPU_CPROFILE_PROCS": _e(
        "observability", "worker/raylet",
        "Comma list of roles to profile when RTPU_CPROFILE_DIR is "
        "set."),
    "RTPU_TASK_EVENTS_BUFFER": _e(
        "observability", "4096",
        "Per-worker task-event ring capacity before drops (drop "
        "counters ride the flush)."),
    "RTPU_TASK_EVENTS_FLUSH_S": _e(
        "observability", "1.0",
        "Task-event batch flush interval to the GCS state engine."),
    "RTPU_TASK_TABLE_MAX": _e(
        "observability", "10000",
        "Bounded GCS task-table size (oldest finished evicted "
        "first)."),
    "RTPU_ACTOR_TASK_EVENTS": _e(
        "observability", "0",
        "Extend the task-event pipeline to actor method calls so "
        "serve request flow reconciles per request (game days enable "
        "it)."),
    "RTPU_TRACING": _e(
        "observability", "1",
        "Master switch for distributed tracing spans."),
    "RTPU_TRACE_SAMPLE": _e(
        "observability", "0.1",
        "Head-sampling probability for traces (slow/failed requests "
        "are always kept)."),
    "RTPU_TRACE_SLOW_S": _e(
        "observability", "1.0",
        "Latency threshold above which a trace is always kept "
        "regardless of sampling."),
    "RTPU_TRACE_BUFFER": _e(
        "observability", "2048",
        "Per-process span ring capacity before drops."),
    "RTPU_TRACE_FLUSH_S": _e(
        "observability", "1.0",
        "Span batch flush interval to the GCS trace table."),
    "RTPU_TRACE_TABLE_MAX": _e(
        "observability", "50000",
        "Bounded GCS trace-table span capacity."),
    "RTPU_TRACE_MAX_SPANS": _e(
        "observability", "512",
        "Per-trace span cap in the GCS trace table."),
    "RTPU_METRICS_SYNC": _e(
        "observability", "0",
        "1 = ship every metric point as its own actor call instead of "
        "the batched background flusher (tests that assert "
        "immediately)."),
    "RTPU_METRICS_FLUSH_S": _e(
        "observability", "1.0",
        "Metrics local-table flush interval (one record_batch call "
        "per interval)."),
    "RTPU_USAGE_STATS_ENABLED": _e(
        "observability", "0",
        "Opt-in anonymous usage stats."),
    # ------------------------------------------------------------- chaos
    "RTPU_CHAOS": _e(
        "chaos", "(unset = off)",
        "Chaos engine config: a bare integer seed or a JSON object "
        "with `seed`/`schedule`/`p`/`delay_s` (docs/"
        "FAULT_TOLERANCE.md); inherited by every spawned process."),
    "RTPU_CHAOS_LOG": _e(
        "chaos", "(unset)",
        "JSONL path where every fired fault is appended (replay "
        "comparisons project the `ts` field away)."),
    # ------------------------------------------------------------- serve
    "RTPU_SERVE_ROUTING": _e(
        "serve", "p2c",
        "Router policy: `p2c` load-aware power-of-two-choices or "
        "`local` in-flight only."),
    "RTPU_SERVE_LOAD_STALENESS_S": _e(
        "serve", "5.0",
        "Max age of replica load reports before the router falls back "
        "to local in-flight counts."),
    "RTPU_SERVE_OVERLOAD_RETRIES": _e(
        "serve", "2",
        "How many other replicas the proxy tries after a shed "
        "(ReplicaOverloadedError) before returning 503."),
    "RTPU_SERVE_MAX_QUEUED": _e(
        "serve", "(per-deployment)",
        "Default bounded ingress queue per replica on top of "
        "max_concurrent_queries; overflow sheds retriably."),
    "RTPU_SERVE_REQUEST_LOG_MAX": _e(
        "serve", "10000",
        "Per-replica request-ledger capacity (game-day reconcile reads "
        "it)."),
    "RTPU_SERVE_ADAPTIVE_BATCH": _e(
        "serve", "1",
        "AIMD adaptive micro-batch wait window (0 = fixed "
        "batch_wait_timeout_s)."),
    "RTPU_SERVE_BATCH_SUBMIT_TIMEOUT_S": _e(
        "serve", "30.0",
        "Watchdog for a wedged batch function: pending items error "
        "instead of waiting forever."),
    "RTPU_SERVE_GRACEFUL_SHUTDOWN_S": _e(
        "serve", "10.0",
        "Drain window for replicas on shutdown/rolling update before "
        "force-kill."),
    "RTPU_SERVE_HEALTH_FAILURES": _e(
        "serve", "3",
        "Consecutive health-check failures before the controller "
        "replaces a replica."),
    "RTPU_SERVE_HEALTH_TIMEOUT_S": _e(
        "serve", "5.0",
        "Per-probe health-check timeout."),
    "RTPU_SERVE_MAX_SURGE": _e(
        "serve", "1",
        "Extra replicas a rolling update may run beyond target while "
        "a wave's new replicas come up (k8s maxSurge analogue)."),
    "RTPU_SERVE_PROXY_ASSIGN_TIMEOUT_S": _e(
        "serve", "15.0",
        "Proxy-side cap on waiting for a replica assignment before "
        "504."),
    # -------------------------------------------------------------- data
    "RTPU_DATA_STREAMING": _e(
        "data", "1",
        "Streaming data-plane executor (0 = bulk materialization "
        "fallback)."),
    "RTPU_DATA_MAX_INFLIGHT_TASKS": _e(
        "data", "(cores-derived)",
        "Streaming executor cap on concurrently in-flight block "
        "tasks."),
    "RTPU_DATA_MAX_BUFFERED_BYTES": _e(
        "data", "(store-derived)",
        "Streaming executor backpressure threshold on buffered block "
        "bytes."),
    "RTPU_DATA_STORE_HIGH_WATERMARK": _e(
        "data", "0.8",
        "Plasma occupancy fraction above which the streaming executor "
        "pauses admission."),
    "RTPU_PUSH_BASED_SHUFFLE": _e(
        "data", "0",
        "Push-based distributed shuffle for AllToAll stages."),
    # ----------------------------------------------------- train/tune/ckpt
    "RTPU_RESULTS_DIR": _e(
        "train", "~/ray_tpu_results",
        "Root directory for trainer/tuner run results and "
        "checkpoints."),
    "RTPU_TUNE_DISK_CKPT": _e(
        "tune", "1",
        "Persist trial checkpoints to disk (0 = in-memory only)."),
    "RTPU_TUNE_SNAPSHOT_PERIOD": _e(
        "tune", "10",
        "Experiment-state snapshot period in seconds."),
    "RTPU_CKPT_ASYNC": _e(
        "checkpoint", "1",
        "Async checkpointer: commit in the background, overlapping "
        "with the next step (0 = synchronous)."),
    "RTPU_CKPT_FSYNC": _e(
        "checkpoint", "1",
        "fsync checkpoint files + dirs before commit (0 trades "
        "durability for speed in tests)."),
    "RTPU_CKPT_VERIFY": _e(
        "checkpoint", "0",
        "Re-read and verify every checkpoint after commit."),
    # ------------------------------------------------------------ gameday
    "RTPU_GAMEDAY_TRACE_MAX": _e(
        "gameday", "(scenario default)",
        "Trace-table capacity override a game-day run configures on "
        "the state engine."),
    # ---------------------------------------------------------------- ops
    "RTPU_ATTN_EXACT": _e(
        "ops", "0",
        "Force the streaming flash-attention kernels (exact "
        "running-max softmax) where logits may exceed the whole-kv "
        "path's static cap; read at trace time."),
    "RTPU_ATTN_DEBUG": _e(
        "ops", "0",
        "Interpreter-mode Pallas attention kernels for debugging."),
    # ------------------------------------------------------------ storage
    "RTPU_STORAGE": _e(
        "storage", "(unset)",
        "Default cluster storage URI (`ray_tpu.init(storage=...)` "
        "fallback; raylets mount it for spill)."),
    "RTPU_WORKFLOW_STORAGE": _e(
        "storage", "(RTPU_STORAGE-derived)",
        "Workflow-engine storage URI override."),
    # ----------------------------------------------------------- runtime_env
    "RTPU_CONTAINER_RUNTIME": _e(
        "runtime_env", "(autodetect)",
        "Container runtime binary for containerized runtime_envs "
        "(podman/docker)."),
    # ------------------------------------------------------------- testing
    "RTPU_SCALE_FULL": _e(
        "testing", "0",
        "Run the scale suite at its full envelope instead of the "
        "CI-sized one."),
    "RTPU_TEST_FLAG": _e(
        "testing", "(unset)",
        "Scratch variable runtime_env tests round-trip through "
        "workers."),
    "RTPU_RAN_IN_CONTAINER": _e(
        "testing", "(unset)",
        "Sentinel the container-runtime_env test's fake runtime "
        "exports."),
    "RTPU_FAKE_CONDA_ENV": _e(
        "testing", "(unset)",
        "Sentinel the conda-runtime_env test's fake activate script "
        "exports."),
    "RTPU_ALLOW_MISSING_DEPS": _e(
        "testing", "0",
        "Let the test session run with optional deps missing instead "
        "of failing collection."),
}


def system_config_vars() -> Dict[str, Dict[str, str]]:
    """``RTPU_<FIELD>`` overrides derived from the SystemConfig
    dataclass — every field is env-overridable via
    ``apply_env_overrides()``."""
    from ray_tpu.common.config import SystemConfig
    out: Dict[str, Dict[str, str]] = {}
    for f in _dc_fields(SystemConfig):
        name = f"RTPU_{f.name.upper()}"
        out[name] = _e("system-config", repr(f.default),
                       f"Overrides `SystemConfig.{f.name}` "
                       f"(ray_tpu/common/config.py) cluster-wide.")
    return out


def _build() -> Dict[str, Dict[str, str]]:
    out = system_config_vars()
    out.update(STATIC_VARS)  # hand-written entries win on collision
    return out


CONFIG_VARS: Dict[str, Dict[str, str]] = _build()
