"""rtpulint output: text (one finding per line, grep-able) and JSON
(stable schema for tooling — the tier-1 gate and the CLI smoke test
both consume it)."""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu.analysis.baseline import BaselineEntry
from ray_tpu.analysis.core import Finding, registry

__all__ = ["render_text", "render_json", "summary_counts"]


def summary_counts(findings: List[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        out[f.code] = out.get(f.code, 0) + 1
    return dict(sorted(out.items()))


def render_text(unsuppressed: List[Finding],
                baselined: Optional[List[Finding]] = None,
                stale: Optional[List[BaselineEntry]] = None,
                files_scanned: int = 0, verbose: bool = False) -> str:
    lines: List[str] = []
    for f in unsuppressed:
        lines.append(f.render())
    if verbose and baselined:
        for f in baselined:
            lines.append(f"{f.render()}  (baselined)")
    for e in stale or []:
        lines.append(
            f"stale baseline entry (finding no longer fires — delete "
            f"it): {e.code} {e.relpath} {e.scope} {e.fingerprint}")
    counts = summary_counts(unsuppressed)
    tally = ", ".join(f"{c}×{n}" for c, n in counts.items()) or "none"
    lines.append(
        f"rtpulint: {len(unsuppressed)} finding(s) [{tally}] in "
        f"{files_scanned} file(s)"
        + (f"; {len(baselined)} baselined" if baselined else "")
        + (f"; {len(stale)} STALE baseline entr(y/ies)" if stale else ""))
    return "\n".join(lines)


def render_json(unsuppressed: List[Finding],
                baselined: Optional[List[Finding]] = None,
                stale: Optional[List[BaselineEntry]] = None,
                files_scanned: int = 0) -> str:
    doc: Dict[str, Any] = {
        "version": 1,
        "files_scanned": files_scanned,
        "counts": summary_counts(unsuppressed),
        "findings": [f.as_dict() for f in unsuppressed],
        "baselined": [f.as_dict() for f in (baselined or [])],
        "stale_baseline": [
            {"code": e.code, "relpath": e.relpath, "scope": e.scope,
             "fingerprint": e.fingerprint, "comment": e.comment}
            for e in (stale or [])],
        "checkers": {code: {"name": cls.name,
                            "description": cls.description}
                     for code, cls in registry().items()},
    }
    return json.dumps(doc, indent=2, sort_keys=True)
