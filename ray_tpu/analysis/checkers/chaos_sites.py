"""RTPU004 — chaos sites must be declared before they're hit.

The chaos engine is only as trustworthy as its site catalog: a typo'd
site string in ``chaos.hit("raylet.dispach")`` silently never fires
and the fault path it was supposed to exercise ships untested. Sites
are therefore *declared* in ``ray_tpu._private.chaos.SITES`` (site →
ops → where injected — the same table docs/FAULT_TOLERANCE.md renders)
and every ``chaos.hit(...)`` call must pass a declared site, as a
string literal or a module-level string constant the checker can
resolve.

The converse direction — every declared site is actually exercised by
``tests/`` — is enforced by the registry round-trip in
``tests/test_static_analysis.py`` (it needs the test tree, which the
per-module checker doesn't see).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ray_tpu.analysis.core import (Checker, Finding, ModuleContext,
                                   call_name, const_str,
                                   module_constants, register)


def _declared_sites(ctx: ModuleContext) -> Set[str]:
    sites = ctx.config.get("chaos_sites")
    if sites is not None:
        return set(sites)
    from ray_tpu._private.chaos import SITES
    return set(SITES)


@register
class ChaosSiteChecker(Checker):
    code = "RTPU004"
    name = "undeclared-chaos-site"
    description = ("chaos.hit(site) literal must match the declared "
                   "site registry in _private/chaos.py")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        # the registry module itself declares the sites; hits inside it
        # are table plumbing, not injection points
        if ctx.relpath.endswith("_private/chaos.py"):
            return []
        out: List[Finding] = []
        sites: Optional[Set[str]] = None
        consts = None
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            # match any `<obj>.hit(...)` leaf: injection points go
            # through `chaos.hit` or a cached engine (`eng.hit`)
            if name is None or not (
                    name.rsplit(".", 1)[-1] == "hit"
                    or name == "chaos_hit"):
                continue
            if not node.args:
                continue
            if sites is None:
                sites = _declared_sites(ctx)
                consts = module_constants(ctx.tree)
            arg = node.args[0]
            site = const_str(arg)
            if site is None and isinstance(arg, ast.Name):
                site = consts.get(arg.id)
            if site is None:
                out.append(ctx.finding(
                    self.code, node,
                    "chaos.hit() site is not a string literal or "
                    "resolvable module-level constant — declared-site "
                    "conformance can't be checked statically"))
                continue
            if site not in sites:
                import difflib
                close = difflib.get_close_matches(site, sorted(sites),
                                                  n=1)
                hint = f" (did you mean `{close[0]}`?)" if close else ""
                out.append(ctx.finding(
                    self.code, node,
                    f"chaos site `{site}` is not declared in "
                    f"chaos.SITES{hint} — declare it (site → ops → "
                    f"where) or fix the literal"))
        return out
