"""RTPU001 — blocking call inside ``async def``.

Every event loop in the substrate (the GCS server, raylet dispatch,
serve router/replica, the LLM engine step loop) multiplexes hundreds
of connections on one thread; a single ``time.sleep`` or sync
``subprocess`` call inside a coroutine stalls all of them — exactly
the class of stall ``RTPU_LOOP_STALL_S`` exists to catch at runtime.
This checker catches it at lint time.

Nested *sync* ``def``s inside a coroutine are not flagged (they run
wherever they're called — typically an executor); ``await
loop.run_in_executor(None, time.sleep, ...)`` passes the callable, not
a call, so it's naturally fine.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu.analysis.core import (Checker, Finding, ModuleContext,
                                   call_name, register,
                                   walk_no_nested_defs)

# dotted call names that block the calling thread. ``config`` key
# ``blocking_calls`` extends/overrides this set per run.
BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.system", "os.waitpid", "os.wait",
    "socket.create_connection", "socket.getaddrinfo",
    "urllib.request.urlopen",
    "requests.get", "requests.post", "requests.put", "requests.request",
    "shutil.copytree", "shutil.rmtree",
}

# method names that block when called on anything (sync socket/file
# drains and queue waits); attribute-only matches, so false positives
# stay possible on unrelated objects — suppress with a pragma when the
# receiver is genuinely non-blocking.
BLOCKING_METHODS = {
    "recv_into",  # sync socket drain
}


@register
class BlockingCallChecker(Checker):
    code = "RTPU001"
    name = "blocking-call-in-async"
    description = ("blocking call (time.sleep, sync subprocess/socket/"
                   "urllib) inside async def stalls the event loop")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        blocking = set(BLOCKING_CALLS)
        blocking |= set(ctx.config.get("blocking_calls", ()))
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for sub in walk_no_nested_defs(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                if name is None:
                    continue
                leaf = name.rsplit(".", 1)[-1]
                if name in blocking or (
                        # match `x.time.sleep`-style rebinds too:
                        # compare the trailing two components
                        "." in name and
                        ".".join(name.rsplit(".", 2)[-2:]) in blocking):
                    out.append(ctx.finding(
                        self.code, sub,
                        f"blocking call `{name}(...)` inside "
                        f"`async def {node.name}` — stalls the event "
                        f"loop; await an async equivalent or move it "
                        f"to an executor"))
                elif leaf in BLOCKING_METHODS and "." in name:
                    out.append(ctx.finding(
                        self.code, sub,
                        f"`{name}(...)` blocks the calling thread "
                        f"inside `async def {node.name}`"))
        return out
