"""RTPU006 — version-gated wire fields need a negotiated-version guard.

Wire schema minors add fields to *pre-existing* methods
(``release_lease.inflight`` at 1.2, ``actor_call.trace_ctx`` at 1.6,
``worker_register.direct_address`` at 1.7, the ``tc`` trace context on
channel frames at 1.6 — the full map is
``ray_tpu._private.schema.FIELD_VERSIONS``). A peer that negotiated an
older minor simply never sends them, so handler code has exactly two
safe ways to touch such a field:

* **absence-tolerant read** — ``payload.get("tc")`` plus a truthiness
  check (the receive-side idiom in ``dag/channel.py`` and
  ``_private/direct.py``: a pre-1.6 owner just never sets ``tc``);
* **hard read under a version guard** — ``payload["inflight"]`` only
  inside a function that consults the negotiated version
  (``conn.meta["peer_protocol_version"]`` / a ``>= (1, N)`` tuple
  compare / a negotiated-feature flag like ``_trace_peers`` computed
  from one, the ``compiled_dag._negotiate`` pattern).

This checker flags hard subscript reads of gated fields on
payload-shaped names (``payload``/``reply``/``frame``/...) in
functions with no recognizable guard — the read that raises
``KeyError`` the day a legacy peer connects.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.analysis.core import (Checker, Finding, ModuleContext,
                                   const_str, dotted_name, register)

# names that hold decoded wire payloads in this codebase's handlers
PAYLOAD_NAMES = {"payload", "reply", "frame", "msg", "message", "resp",
                 "response", "req", "request", "r", "body"}

# identifiers that mark a function as version-aware
_GUARD_ATTR_RE = re.compile(
    r"peer_protocol_version|peer_ver|min_peer|negotiat|_trace_peers"
    r"|protocol_version")


def _field_versions(ctx: ModuleContext) -> Dict[str, Tuple[int, int]]:
    """field name -> version introduced, for fields added to
    pre-existing methods after 1.0 (the gated set)."""
    fv = ctx.config.get("field_versions")
    if fv is None:
        from ray_tpu._private.schema import FIELD_VERSIONS
        fv = FIELD_VERSIONS
    out: Dict[str, Tuple[int, int]] = {}
    for key, ver in fv.items():
        field = key[1] if isinstance(key, tuple) else \
            str(key).rsplit(".", 1)[-1]
        ver = tuple(ver)
        if ver > (1, 0):
            prev = out.get(field)
            if prev is None or ver < prev:
                out[field] = ver  # earliest introduction wins
    return out


def _has_version_guard(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Attribute) and _GUARD_ATTR_RE.search(
                sub.attr):
            return True
        if isinstance(sub, ast.Name) and _GUARD_ATTR_RE.search(sub.id):
            return True
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and _GUARD_ATTR_RE.search(sub.value):
            return True
        if isinstance(sub, ast.Compare):
            for comp in sub.comparators:
                if isinstance(comp, ast.Tuple) and \
                        len(comp.elts) == 2 and all(
                            isinstance(e, ast.Constant) and
                            isinstance(e.value, int)
                            for e in comp.elts):
                    return True
    return False


@register
class WireVersionChecker(Checker):
    code = "RTPU006"
    name = "unguarded-versioned-field"
    description = ("hard read of a wire field introduced after schema "
                   "1.0 without a negotiated-version guard — breaks "
                   "against legacy peers")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        gated: Optional[Dict[str, Tuple[int, int]]] = None
        guarded_fns: Dict[int, bool] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Subscript):
                continue
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                continue  # writing/deleting a field we produce is fine
            base = dotted_name(node.value)
            if base is None or base.split(".")[-1] not in PAYLOAD_NAMES:
                continue
            field = const_str(node.slice)
            if field is None:
                continue
            if gated is None:
                gated = _field_versions(ctx)
            ver = gated.get(field)
            if ver is None:
                continue
            fn = self._enclosing_fn(ctx, node)
            if fn is None:
                continue
            key = id(fn)
            if key not in guarded_fns:
                guarded_fns[key] = _has_version_guard(fn)
            if guarded_fns[key]:
                continue
            out.append(ctx.finding(
                self.code, node,
                f"`{base}[\"{field}\"]` reads a schema-"
                f"{ver[0]}.{ver[1]} field without a negotiated-"
                f"version guard — a pre-{ver[0]}.{ver[1]} peer never "
                f"sends it; use .get() with an absence check or gate "
                f"on conn.meta[\"peer_protocol_version\"]"))
        return out

    @staticmethod
    def _enclosing_fn(ctx: ModuleContext, node: ast.AST
                      ) -> Optional[ast.AST]:
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None
