"""RTPU003 — unpaired resource acquire/release.

Three concrete leak shapes from the bug history, all checked at class
granularity (a long-lived object owns the resource; pairing inside a
single call is often legitimately split across methods):

* **refcount pairing** — a class whose methods call ``*.incref(...)``
  but never ``decref`` anywhere leaks shared pages the moment an error
  path skips the happy-path release (the PR-12 KV-page class needed a
  zero-leaked-pages gate for exactly this). Additionally, a *function*
  that increfs and then decrefs only on the straight-line path — with
  fallible calls in between and no ``try/finally``/``except`` guarding
  the decref — leaks on the error path.
* **span pairing** — opening a tracing span (``tracing.Span(...)`` /
  ``start_span``) without ``finish``/``end``/``__exit__`` in the same
  class leaves the span out of the trace tree forever (breaks the
  tree-completeness reconcile).
* **daemon-thread lifecycle** — a class that starts a
  ``threading.Thread(daemon=True)`` and has no ``join``/stop path
  (``stop``/``close``/``shutdown``/``__exit__``/an ``Event.set`` the
  loop polls) re-leaks a thread per instance: the rtpu-data-prefetch
  leak (PR 1) and the tracing-flusher leak (PR 9), twice-learned.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ray_tpu.analysis.core import (Checker, Finding, ModuleContext,
                                   call_name, register)

_STOPPISH_METHODS = {
    "stop", "close", "shutdown", "join", "stop_all", "teardown",
    "__exit__", "__del__", "drain", "abort", "cancel", "stop_flusher",
}
_SPAN_OPENERS = {"start_span", "Span"}
_SPAN_CLOSERS = {"finish", "end", "end_span", "record_span"}


def _attr_calls(node: ast.AST) -> Iterable[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _leaf(call: ast.Call) -> Optional[str]:
    name = call_name(call)
    return name.rsplit(".", 1)[-1] if name else None


def _has_daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _names_in(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            out.add(sub.attr)
        elif isinstance(sub, ast.Name):
            out.add(sub.id)
    return out


@register
class ResourcePairingChecker(Checker):
    code = "RTPU003"
    name = "unpaired-acquire-release"
    description = ("incref without decref, span open without close, or "
                   "daemon thread started by a long-lived object with "
                   "no stop/join path")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_error_path(ctx, node))
        return out

    # ------------------------------------------------------ class pairing

    def _check_class(self, ctx: ModuleContext, cls: ast.ClassDef
                     ) -> Iterable[Finding]:
        leaves: List[str] = []
        first_incref: Optional[ast.Call] = None
        first_span: Optional[ast.Call] = None
        thread_start: Optional[ast.Call] = None
        method_names = {n.name for n in cls.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        span_is_ctxmgr = False
        for call in _attr_calls(cls):
            leaf = _leaf(call)
            if leaf is None:
                continue
            leaves.append(leaf)
            if leaf == "incref" and first_incref is None:
                first_incref = call
            if leaf in _SPAN_OPENERS and first_span is None:
                first_span = call
                # `with tracing.Span(...)` / `with span_if(...)` closes
                # via __exit__
                parent = ctx.parent(call)
                if isinstance(parent, ast.withitem):
                    span_is_ctxmgr = True
            if leaf == "Thread" and _has_daemon_true(call):
                thread_start = call

        leafset = set(leaves)
        if first_incref is not None and "decref" not in leafset:
            yield ctx.finding(
                self.code, first_incref,
                f"class `{cls.name}` calls incref but never decref — "
                f"refcounted pages leak on every path; pair the "
                f"release (error paths included)")
        if first_span is not None and not span_is_ctxmgr \
                and not (leafset & _SPAN_CLOSERS):
            yield ctx.finding(
                self.code, first_span,
                f"class `{cls.name}` opens tracing spans but never "
                f"finishes them — incomplete trace trees; use `with` "
                f"or call .finish()")
        if thread_start is not None and "start" in leafset:
            has_stop = bool(method_names & _STOPPISH_METHODS) \
                or "join" in leafset \
                or ("set" in leafset and any(
                    "stop" in n or "shutdown" in n or "exit" in n
                    for n in _names_in(cls)))
            if not has_stop:
                yield ctx.finding(
                    self.code, thread_start,
                    f"class `{cls.name}` starts a daemon thread but "
                    f"has no join/stop path (no "
                    f"stop/close/shutdown/join) — threads accumulate "
                    f"per instance (the rtpu-data-prefetch bug class)")

    # ------------------------------------------- intra-function error path

    def _check_error_path(self, ctx: ModuleContext,
                          fn: ast.AST) -> Iterable[Finding]:
        """incref then decref in one function, with fallible work
        between and the decref not exception-guarded → leaks when that
        work raises."""
        body_stmts = list(fn.body)
        increfs: List[ast.Call] = []
        decrefs: List[ast.Call] = []
        for call in _attr_calls(fn):
            leaf = _leaf(call)
            if leaf == "incref":
                increfs.append(call)
            elif leaf == "decref":
                decrefs.append(call)
        if not increfs or not decrefs:
            return
        guarded = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Try):
                regions = list(sub.finalbody) + [
                    h for h in sub.handlers]
                for region in regions:
                    for c in _attr_calls(region):
                        if _leaf(c) == "decref":
                            guarded.add(id(c))
        if all(id(d) not in guarded for d in decrefs):
            first_inc = min(increfs, key=lambda c: c.lineno)
            last_dec = max(decrefs, key=lambda c: c.lineno)
            # fallible work between acquire and release?
            fallible = [
                c for c in _attr_calls(fn)
                if first_inc.lineno < c.lineno < last_dec.lineno
                and _leaf(c) not in ("incref", "decref", "append",
                                     "get", "len")]
            has_await = any(
                isinstance(s, ast.Await) and
                first_inc.lineno < s.lineno < last_dec.lineno
                for s in ast.walk(fn))
            if fallible or has_await:
                yield ctx.finding(
                    self.code, first_inc,
                    f"incref at line {first_inc.lineno} is released "
                    f"only on the straight-line path (decref line "
                    f"{last_dec.lineno}, not in finally/except) — an "
                    f"exception in between leaks the reference")
