"""Built-in rtpulint checkers. Importing this package registers every
checker with the core registry — ``core.registry()`` does it lazily.

| code    | name                        | invariant                     |
|---------|-----------------------------|-------------------------------|
| RTPU001 | blocking-call-in-async      | no blocking calls on a loop   |
| RTPU002 | lock-across-await           | thread locks don't span await |
| RTPU003 | unpaired-acquire-release    | incref/span/thread pairing    |
| RTPU004 | undeclared-chaos-site       | chaos.hit sites are declared  |
| RTPU005 | unregistered-env-var        | RTPU_* reads are registered   |
| RTPU006 | unguarded-versioned-field   | wire minors gate their fields |
| RTPU007 | silent-swallow-in-loop      | control loops log swallows    |
"""

from ray_tpu.analysis.checkers import (  # noqa: F401
    blocking,
    chaos_sites,
    env_registry,
    excepts,
    locks,
    resources,
    wire_versions,
)
