"""RTPU007 — silent exception swallow in a reconcile/control loop.

The controller tick, raylet dispatch loop, GCS serving loop and LLM
engine step loop are all shaped ``while True: try: ... except
Exception: <keep going>``. Keep-going is correct — a control loop must
survive anything — but *silent* keep-going turns real faults into
permanent mysteries: the loop spins, the subsystem is broken, and
nothing says why. Every swallow in a loop must either log (with
context) or re-raise; ``pass`` is only acceptable with an inline
pragma explaining why the error is genuinely meaningless.

Scope: ``except Exception:``/bare ``except:`` handlers that (a) sit
inside a ``while``/``for`` loop in the same function, (b) are
*inert* — every statement is ``pass``/``continue``/``break``/a bare
constant, so the error is neither logged, re-raised, recorded, nor
handled in any way, and (c) live in a control-plane module
(``controller``, ``raylet``, ``gcs``, ``engine``, ``reconcile``,
``runner`` — override with config key ``reconcile_modules``).
Handlers that do *anything* with the failure (requeue, dead-list the
peer, stash ``_last_error``) are deliberate keep-going policies, not
silence.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from ray_tpu.analysis.core import (Checker, Finding, ModuleContext,
                                   register)

_DEFAULT_MODULE_RE = (
    r"(controller|raylet|gcs|engine|reconcile|runner|disagg|router)"
    r"[^/]*\.py$")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    if isinstance(t, ast.Name) and t.id in ("Exception", "BaseException"):
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and
                   e.id in ("Exception", "BaseException")
                   for e in t.elts)
    return False


def _handler_inert(handler: ast.ExceptHandler) -> bool:
    """True when the handler does literally nothing with the error —
    only pass/continue/break/constant expressions."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def _in_loop(ctx: ModuleContext, handler: ast.ExceptHandler) -> bool:
    for anc in ctx.ancestors(handler):
        if isinstance(anc, (ast.While, ast.For, ast.AsyncFor)):
            return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False  # left the function without crossing a loop
    return False


@register
class SilentExceptChecker(Checker):
    code = "RTPU007"
    name = "silent-swallow-in-loop"
    description = ("except Exception/bare except inside a control-"
                   "plane loop that neither logs nor re-raises")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        mod_re = ctx.config.get("reconcile_modules", _DEFAULT_MODULE_RE)
        if not re.search(mod_re, ctx.relpath):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if not _in_loop(ctx, node):
                continue
            if not _handler_inert(node):
                continue
            out.append(ctx.finding(
                self.code, node,
                "broad except inside a control-loop is inert — the "
                "loop keeps spinning with the fault invisible; log "
                "it with context, record it, or narrow the except"))
        return out
