"""RTPU005 — every ``RTPU_*`` env read must be in the config registry.

~69 ``RTPU_*`` environment variables steer the runtime, and nothing
ever tied them together: a typo'd read (``RTPU_TRACE_SAMPEL``) is a
knob that silently does nothing, and an undocumented knob might as
well not exist. The authoritative registry is
``ray_tpu.analysis.config_registry.CONFIG_VARS`` (rendered to
docs/CONFIGURATION.md by ``python -m ray_tpu.analysis --gen-docs``);
this checker finds every environment read of an ``RTPU_*`` name —
``os.environ.get/[]/setdefault``, ``os.getenv``, ``in os.environ`` —
and fails on names missing from the registry, with near-miss typo
detection against the registered names.

Stale registry entries (registered but never read) are caught by the
round-trip test in ``tests/test_static_analysis.py``.
"""

from __future__ import annotations

import ast
import difflib
from typing import Iterable, List, Optional, Set, Tuple

from ray_tpu.analysis.core import (Checker, Finding, ModuleContext,
                                   call_name, const_str, dotted_name,
                                   register)


def env_reads(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    """All (env-var-name, node) reads in the module, every access
    idiom. Only constant-resolvable names are returned."""
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is None:
                continue
            if name.endswith("os.getenv") or name == "getenv":
                if node.args:
                    v = const_str(node.args[0])
                    if v:
                        out.append((v, node))
            elif name.endswith("environ.get") \
                    or name.endswith("environ.setdefault") \
                    or name.endswith("environ.pop"):
                if node.args:
                    v = const_str(node.args[0])
                    if v:
                        out.append((v, node))
        elif isinstance(node, ast.Subscript):
            base = dotted_name(node.value)
            if base and base.endswith("environ"):
                v = const_str(node.slice)
                if v:
                    out.append((v, node))
        elif isinstance(node, ast.Compare):
            # "X" in os.environ
            if len(node.ops) == 1 and isinstance(node.ops[0],
                                                 (ast.In, ast.NotIn)):
                base = dotted_name(node.comparators[0])
                if base and base.endswith("environ"):
                    v = const_str(node.left)
                    if v:
                        out.append((v, node))
    return out


def _registered(ctx: ModuleContext) -> Set[str]:
    reg = ctx.config.get("env_registry")
    if reg is not None:
        return set(reg)
    from ray_tpu.analysis.config_registry import CONFIG_VARS
    return set(CONFIG_VARS)


@register
class EnvRegistryChecker(Checker):
    code = "RTPU005"
    name = "unregistered-env-var"
    description = ("RTPU_* env read missing from the config registry "
                   "(docs/CONFIGURATION.md) — typo or undocumented "
                   "knob")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        registered: Optional[Set[str]] = None
        for name, node in env_reads(ctx.tree):
            if not name.startswith("RTPU_"):
                continue
            if registered is None:
                registered = _registered(ctx)
            if name in registered:
                continue
            close = difflib.get_close_matches(
                name, sorted(registered), n=1, cutoff=0.8)
            if close:
                msg = (f"env var `{name}` is not in the config "
                       f"registry — near-miss of registered "
                       f"`{close[0]}`; likely a typo")
            else:
                msg = (f"env var `{name}` is not in the config "
                       f"registry — add it to "
                       f"analysis/config_registry.py (and regenerate "
                       f"docs/CONFIGURATION.md) or remove the read")
            out.append(ctx.finding(self.code, node, msg))
        return out
