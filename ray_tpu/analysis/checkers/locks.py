"""RTPU002 — thread lock held across ``await``.

The PR-4 ``ReplicaSet.assign`` race was exactly this shape: a
``threading.Lock`` taken in a coroutine, an ``await`` inside the
``with`` body, and a second task re-entering while the first was
suspended — the lock serializes *threads*, not *tasks*, so the
critical section silently stopped being one. Worse, if another
coroutine on the same loop tries the same lock it deadlocks the whole
loop (the holder can only resume on the thread the waiter is
blocking).

Flagged: a sync ``with`` statement whose context expression names a
lock (leaf identifier contains ``lock`` or ``mutex``) containing an
``await``/``async for``/``async with`` that executes while the lock is
held. ``async with`` on an ``asyncio.Lock`` is the correct idiom and
is not flagged. Nested function bodies are skipped (they don't execute
under the lock).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ray_tpu.analysis.core import (Checker, Finding, ModuleContext,
                                   dotted_name, register,
                                   walk_no_nested_defs)

_LOCKISH = ("lock", "mutex", "sem")


def _lock_leaf(expr: ast.AST) -> Optional[str]:
    """The lock-ish identifier a with-item takes, if any. Handles
    ``with self._lock:``, ``with lock:``, and acquire-style calls
    (``with self._lock.acquire_timeout(1):`` still holds the lock)."""
    target = expr
    if isinstance(target, ast.Call):
        target = target.func
    name = dotted_name(target)
    if name is None:
        return None
    for part in name.split("."):
        low = part.lower()
        if any(t in low for t in _LOCKISH):
            return name
    return None


@register
class LockAcrossAwaitChecker(Checker):
    code = "RTPU002"
    name = "lock-across-await"
    description = ("sync `with <lock>:` body containing await — the "
                   "critical section breaks on suspension and can "
                   "deadlock the loop")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            lock_name = None
            for item in node.items:
                lock_name = _lock_leaf(item.context_expr)
                if lock_name:
                    break
            if not lock_name:
                continue
            for sub in walk_no_nested_defs(node):
                if isinstance(sub, (ast.Await, ast.AsyncFor,
                                    ast.AsyncWith)):
                    kind = type(sub).__name__.lower()
                    out.append(ctx.finding(
                        self.code, sub,
                        f"`{kind}` at line {sub.lineno} while holding "
                        f"`{lock_name}` (taken line {node.lineno}) — a "
                        f"thread lock does not protect across task "
                        f"suspension; narrow the critical section or "
                        f"use asyncio.Lock with `async with`"))
                    break  # one finding per with-block is enough
        return out
