from ray_tpu.experimental.state.api import (list_actors, list_jobs,
                                            list_nodes,
                                            list_placement_groups,
                                            summarize_cluster)

__all__ = ["list_actors", "list_jobs", "list_nodes",
           "list_placement_groups", "summarize_cluster"]
