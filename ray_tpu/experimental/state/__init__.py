from ray_tpu.experimental.state.api import (StateListResult, list_actors,
                                            list_jobs, list_nodes,
                                            list_objects,
                                            list_placement_groups,
                                            list_tasks, summarize_cluster,
                                            summarize_tasks)

__all__ = ["StateListResult", "list_actors", "list_jobs", "list_nodes",
           "list_objects", "list_placement_groups", "list_tasks",
           "summarize_cluster", "summarize_tasks"]
