"""Cluster state API — `list tasks/objects/actors/nodes/jobs/placement
groups` with cursor pagination and server-side filtering.

Reference analogue: python/ray/experimental/state/api.py (+ the
dashboard-side state_aggregator.py). Queries go straight to the GCS
over the driver's existing connection. Every list call is PAGED on the
wire (``limit`` + ``continuation_token`` + filter pushdown): a single
RPC never carries a full table, and the client either walks pages
transparently (default) or hands control of the cursor to the caller
(pass ``page_size``/``continuation_token``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as _worker_mod

# page size used when the client auto-walks the cursor for callers
# that just want "the list"
_AUTO_PAGE = 1000


class StateListResult(list):
    """A plain list of rows plus the paging metadata that rode the last
    reply: ``next_token`` (None = exhausted), ``total`` (server-side
    filtered count), ``dropped`` (records the bounded server table has
    evicted — >0 means history, not live state, is missing)."""

    def __init__(self, items=(), next_token: Optional[str] = None,
                 total: Optional[int] = None, dropped: int = 0):
        super().__init__(items)
        self.next_token = next_token
        self.total = total
        self.dropped = dropped


def _gcs_call(method: str, payload: Optional[dict] = None,
              timeout: float = 30) -> dict:
    w = _worker_mod._global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu is not initialized")
    return w.call_sync(w.gcs, method, payload or {}, timeout=timeout)


def _list_paged(method: str, filters: Optional[Dict[str, Any]],
                limit: Optional[int], continuation_token: Optional[str],
                page_size: Optional[int], timeout: float = 30,
                extra: Optional[dict] = None) -> StateListResult:
    """Shared cursor walker. Explicit ``page_size``/``continuation_
    token`` = manual paging (ONE page per call, resume via
    ``.next_token``); otherwise pages are walked transparently until
    ``limit`` (or the table) is exhausted."""
    manual = page_size is not None or continuation_token is not None
    token = continuation_token
    out = StateListResult()
    while True:
        want = page_size or _AUTO_PAGE
        if limit is not None:
            want = min(want, max(1, limit - len(out)))
        payload = {"paged": True, "limit": want,
                   "continuation_token": token, **(extra or {})}
        if filters:
            payload["filters"] = filters
        r = _gcs_call(method, payload, timeout=timeout)
        out.extend(r.get("items") or ())
        out.next_token = r.get("next_token")
        out.total = r.get("total", out.total)
        out.dropped = r.get("dropped", out.dropped)
        token = out.next_token
        if manual or token is None or \
                (limit is not None and len(out) >= limit):
            return out


def list_nodes(filters: Optional[Dict[str, Any]] = None,
               limit: Optional[int] = None,
               continuation_token: Optional[str] = None,
               page_size: Optional[int] = None) -> StateListResult:
    return _list_paged("get_nodes", filters, limit, continuation_token,
                       page_size)


def list_actors(filters: Optional[Dict[str, Any]] = None,
                limit: Optional[int] = None,
                continuation_token: Optional[str] = None,
                page_size: Optional[int] = None) -> StateListResult:
    return _list_paged("list_actors", filters, limit, continuation_token,
                       page_size)


def list_tasks(filters: Optional[Dict[str, Any]] = None,
               limit: Optional[int] = None,
               continuation_token: Optional[str] = None,
               page_size: Optional[int] = None) -> StateListResult:
    """Cluster-wide task listing from the GCS's bounded task table
    (fed by the task-event pipeline). Filter keys: state, name,
    job_id, node_id, task_id — pushed down to the server. The result's
    ``dropped`` reports table evictions (cap exceeded)."""
    return _list_paged("list_tasks", filters, limit, continuation_token,
                       page_size)


def list_objects(filters: Optional[Dict[str, Any]] = None,
                 limit: Optional[int] = None,
                 continuation_token: Optional[str] = None,
                 page_size: Optional[int] = None,
                 node_id: Optional[str] = None) -> StateListResult:
    """Cluster object listing aggregated from per-raylet plasma
    indexes (pinned + spilled primaries); each row carries locations,
    owner, size. ``node_id`` narrows the fan-out to one raylet."""
    return _list_paged("list_objects", filters, limit,
                       continuation_token, page_size,
                       extra={"node_id": node_id} if node_id else None)


def profile_stacks(node_id: Optional[str] = None,
                   worker_id: Optional[str] = None) -> Dict[str, Any]:
    """Live stack snapshot of workers (reference:
    dashboard/modules/reporter/profile_manager.py — on-demand worker
    profiling; faulthandler-style dumps here)."""
    return _gcs_call("profile_stacks",
                     {"node_id": node_id, "worker_id": worker_id})


def profile_flamegraph(node_id: Optional[str] = None,
                       worker_id: Optional[str] = None,
                       duration_s: float = 2.0,
                       interval_s: Optional[float] = None
                       ) -> Dict[str, Any]:
    """Timed sampling profile of workers -> folded stacks (the
    flamegraph-collapsed format flamegraph.pl and speedscope import;
    reference: profile_manager.py py-spy flamegraphs)."""
    return _gcs_call("profile_flamegraph",
                     {"node_id": node_id, "worker_id": worker_id,
                      "duration_s": duration_s,
                      "interval_s": interval_s},
                     # the whole GCS->raylet->worker chain runs for
                     # duration_s before replying
                     timeout=min(float(duration_s), 30.0) + 25)


def node_stats(node_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Per-node agent snapshots: physical cpu/mem/disk plus the native
    scheduler/object-store gauges (reference: dashboard/agent.py
    reporter + src/ray/stats/metric_defs.cc)."""
    return _gcs_call("get_node_stats", {"node_id": node_id})["nodes"]


def list_jobs(filters: Optional[Dict[str, Any]] = None,
              limit: Optional[int] = None,
              continuation_token: Optional[str] = None,
              page_size: Optional[int] = None) -> StateListResult:
    return _list_paged("get_jobs", filters, limit, continuation_token,
                       page_size)


def list_placement_groups(filters: Optional[Dict[str, Any]] = None,
                          limit: Optional[int] = None,
                          continuation_token: Optional[str] = None,
                          page_size: Optional[int] = None
                          ) -> StateListResult:
    return _list_paged("list_placement_groups", filters, limit,
                       continuation_token, page_size)


def list_cluster_events(limit: int = 200,
                        severity: Optional[str] = None
                        ) -> List[Dict[str, Any]]:
    """Structured events from the GCS ring (reference: `ray list
    cluster-events` / dashboard event browsing)."""
    return _gcs_call("list_events", {"limit": limit,
                                     "severity": severity})


def list_logs() -> List[str]:
    """Names of log files under the driver's session dir (reference:
    `ray logs` listing via the dashboard agent)."""
    import os
    w = _worker_mod._global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu is not initialized")
    d = os.path.join(w.session_dir, "logs")
    out = []
    for root, _dirs, files in os.walk(d):
        rel = os.path.relpath(root, d)
        for f in sorted(files):
            out.append(f if rel == "." else os.path.join(rel, f))
    return sorted(out)


def get_log(filename: str, tail: int = 1000) -> str:
    """Tail a session log file by its list_logs name."""
    import os
    w = _worker_mod._global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu is not initialized")
    base = os.path.realpath(os.path.join(w.session_dir, "logs"))
    path = os.path.realpath(os.path.join(base, filename))
    if not path.startswith(base + os.sep):
        raise ValueError(f"log path escapes the session dir: {filename!r}")
    from collections import deque
    with open(path, errors="replace") as f:
        # bounded: never load a multi-GB log whole to return its tail
        lines = deque(f, maxlen=tail)
    return "".join(lines)


def summarize_cluster() -> Dict[str, Any]:
    """One-RPC cluster summary: the GCS counts its own tables
    (node/actor/job/PG/task counts + resource totals) instead of
    shipping them whole just to be len()'d client-side."""
    return _gcs_call("summarize")


def list_traces(filters: Optional[Dict[str, Any]] = None,
                limit: Optional[int] = None,
                continuation_token: Optional[str] = None,
                page_size: Optional[int] = None) -> StateListResult:
    """Trace summaries from the GCS's bounded trace table (explicit
    spans + task-only traces): {trace_id, root, spans, start_ts,
    duration_s, status}. Filter keys (pushed down): status, root.
    ``dropped`` reports spans the bounded table has evicted."""
    return _list_paged("list_traces", filters, limit,
                       continuation_token, page_size)


def get_trace(trace_id: str) -> Dict[str, Any]:
    """One trace's full span set in one RPC: explicit spans (serve
    request, dag hops, object pulls) merged with task-lifecycle spans
    the GCS synthesizes from the task table. Feed the result's
    ``spans`` to ``tracing.critical_path`` / ``tracing.tree_complete``
    (docs/TRACING.md)."""
    return _gcs_call("get_trace", {"trace_id": trace_id})


def summarize_tasks() -> Dict[str, Any]:
    """Per-function task aggregation (`ray-tpu summary tasks`):
    {summary: [{name, count, by_state, mean_duration_s}, ...],
    by_state, dropped, ...} computed GCS-side over the bounded task
    table."""
    return _gcs_call("summarize_tasks")
