"""Cluster state API — `list actors/nodes/jobs/placement groups`.

Reference analogue: python/ray/experimental/state/api.py (+ the
dashboard-side state_aggregator.py). Queries go straight to the GCS
over the driver's existing connection.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as _worker_mod


def _gcs_call(method: str, payload: Optional[dict] = None,
              timeout: float = 30) -> dict:
    w = _worker_mod._global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu is not initialized")
    return w.call_sync(w.gcs, method, payload or {}, timeout=timeout)


def list_nodes(filters: Optional[Dict[str, Any]] = None
               ) -> List[Dict[str, Any]]:
    return _apply_filters(_gcs_call("get_nodes"), filters)


def list_actors(filters: Optional[Dict[str, Any]] = None
                ) -> List[Dict[str, Any]]:
    return _apply_filters(_gcs_call("list_actors"), filters)


def profile_stacks(node_id: Optional[str] = None,
                   worker_id: Optional[str] = None) -> Dict[str, Any]:
    """Live stack snapshot of workers (reference:
    dashboard/modules/reporter/profile_manager.py — on-demand worker
    profiling; faulthandler-style dumps here)."""
    return _gcs_call("profile_stacks",
                     {"node_id": node_id, "worker_id": worker_id})


def profile_flamegraph(node_id: Optional[str] = None,
                       worker_id: Optional[str] = None,
                       duration_s: float = 2.0,
                       interval_s: Optional[float] = None
                       ) -> Dict[str, Any]:
    """Timed sampling profile of workers -> folded stacks (the
    flamegraph-collapsed format flamegraph.pl and speedscope import;
    reference: profile_manager.py py-spy flamegraphs)."""
    return _gcs_call("profile_flamegraph",
                     {"node_id": node_id, "worker_id": worker_id,
                      "duration_s": duration_s,
                      "interval_s": interval_s},
                     # the whole GCS->raylet->worker chain runs for
                     # duration_s before replying
                     timeout=min(float(duration_s), 30.0) + 25)


def node_stats(node_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Per-node agent snapshots: physical cpu/mem/disk plus the native
    scheduler/object-store gauges (reference: dashboard/agent.py
    reporter + src/ray/stats/metric_defs.cc)."""
    return _gcs_call("get_node_stats", {"node_id": node_id})["nodes"]


def list_jobs(filters: Optional[Dict[str, Any]] = None
              ) -> List[Dict[str, Any]]:
    return _apply_filters(_gcs_call("get_jobs"), filters)


def list_placement_groups(filters: Optional[Dict[str, Any]] = None
                          ) -> List[Dict[str, Any]]:
    return _apply_filters(_gcs_call("list_placement_groups"), filters)


def list_cluster_events(limit: int = 200,
                        severity: Optional[str] = None
                        ) -> List[Dict[str, Any]]:
    """Structured events from the GCS ring (reference: `ray list
    cluster-events` / dashboard event browsing)."""
    return _gcs_call("list_events", {"limit": limit,
                                     "severity": severity})


def list_logs() -> List[str]:
    """Names of log files under the driver's session dir (reference:
    `ray logs` listing via the dashboard agent)."""
    import os
    w = _worker_mod._global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu is not initialized")
    d = os.path.join(w.session_dir, "logs")
    out = []
    for root, _dirs, files in os.walk(d):
        rel = os.path.relpath(root, d)
        for f in sorted(files):
            out.append(f if rel == "." else os.path.join(rel, f))
    return sorted(out)


def get_log(filename: str, tail: int = 1000) -> str:
    """Tail a session log file by its list_logs name."""
    import os
    w = _worker_mod._global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu is not initialized")
    base = os.path.realpath(os.path.join(w.session_dir, "logs"))
    path = os.path.realpath(os.path.join(base, filename))
    if not path.startswith(base + os.sep):
        raise ValueError(f"log path escapes the session dir: {filename!r}")
    from collections import deque
    with open(path, errors="replace") as f:
        # bounded: never load a multi-GB log whole to return its tail
        lines = deque(f, maxlen=tail)
    return "".join(lines)


def summarize_cluster() -> Dict[str, Any]:
    nodes = list_nodes()
    actors = list_actors()
    return {
        "nodes_total": len(nodes),
        "nodes_alive": sum(1 for n in nodes if n.get("alive")),
        "actors_total": len(actors),
        "actors_alive": sum(1 for a in actors
                            if a.get("state") == "ALIVE"),
        "cluster_resources": _gcs_call("cluster_resources"),
        "available_resources": _gcs_call("available_resources"),
    }


def _apply_filters(rows: List[Dict[str, Any]],
                   filters: Optional[Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    if not filters:
        return rows
    return [r for r in rows
            if all(r.get(k) == v for k, v in filters.items())]
