"""Replayable game-day scenario specs.

A scenario composes three schedules that previously only existed in
separate test suites, under ONE seed:

* the open-loop load phases (``loadgen.build_schedule``),
* timed control-plane actions the runner executes (rolling update,
  explicit scale changes),
* the chaos engine's fault schedule (controller / replica SIGKILLs at
  exact hit counts — PR 4 semantics: the N-th control-loop tick, the
  N-th accepted request).

Everything derives deterministically from the spec: ``chaos_config``
and ``build_schedule`` are pure functions, so replaying a scenario
with the same seed reproduces the same arrivals (ids included) and the
same fault schedule — the property the flagship tier-1 gate asserts.

Scenarios are plain dict-shaped and JSON-loadable (``load_scenario``
accepts a builtin name or a ``.json`` path), so a new workload ships
its game day as a spec file, not a bespoke test harness.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ray_tpu.gameday.loadgen import ArrivalSchedule, build_schedule

DEPLOYMENT_NAME = "GameDay"


class Scenario:
    """One game day: load shapes + timed actions + fault schedule +
    the SLO it is graded against."""

    def __init__(self, name: str, *, seed: int,
                 phases: List[Dict[str, Any]],
                 actions: Optional[List[Dict[str, Any]]] = None,
                 deployment: Optional[Dict[str, Any]] = None,
                 slo: Optional[Dict[str, Any]] = None,
                 tenants: int = 4, tenant_skew: float = 1.2,
                 max_workers: int = 32,
                 tolerate_lost_server_records: bool = False,
                 description: str = ""):
        self.name = name
        self.seed = int(seed)
        self.phases = phases
        self.actions = actions or []
        self.deployment = {
            "num_replicas": 3,
            "max_concurrent_queries": 16,
            "max_queued_requests": 64,
            "service_time_ms": 3.0,
            "graceful_shutdown_timeout_s": 10.0,
            # router admission bound: an arrival not placeable within
            # this window is shed client-side (the proxy's 503)
            "assign_timeout_s": 30.0,
            **(deployment or {}),
        }
        self.slo = {
            "availability_target": 0.999,
            "latency_target_ms": None,
            "count_shed_as_bad": False,
            **(slo or {}),
        }
        self.tenants = tenants
        self.tenant_skew = tenant_skew
        self.max_workers = max_workers
        # scenarios that SIGKILL replicas lose those replicas' ledgers;
        # the reconciler then tolerates client-ok requests whose server
        # record died with the replica (counted, reported, not failed)
        self.tolerate_lost_server_records = tolerate_lost_server_records
        self.description = description

    # ---- derived, deterministic schedules ----

    def arrival_schedule(self, scale: float = 1.0) -> ArrivalSchedule:
        """``scale`` stretches phase durations (0.5 = half-length game
        day) without touching rates, ids or the seed."""
        phases = [dict(p, duration_s=float(p.get("duration_s", 0.0))
                       * scale) for p in self.phases]
        return build_schedule(phases, self.seed, name=self.name,
                              tenants=self.tenants,
                              tenant_skew=self.tenant_skew)

    def timed_actions(self, scale: float = 1.0) -> List[Dict[str, Any]]:
        """Runner-executed actions, time-scaled like the load."""
        out = []
        for a in self.actions:
            if a["kind"] in ("rolling_update", "scale"):
                out.append(dict(a, t_s=float(a.get("t_s", 0.0)) * scale))
        return sorted(out, key=lambda a: a["t_s"])

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "seed": self.seed,
            "description": self.description,
            "phases": self.phases, "actions": self.actions,
            "deployment": self.deployment, "slo": self.slo,
            "tenants": self.tenants, "tenant_skew": self.tenant_skew,
            "max_workers": self.max_workers,
            "tolerate_lost_server_records":
                self.tolerate_lost_server_records,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Scenario":
        d = dict(d)
        name = d.pop("name")
        seed = d.pop("seed", 0)
        phases = d.pop("phases")
        return cls(name, seed=seed, phases=phases, **d)


def chaos_config(scenario: Scenario) -> Optional[Dict[str, Any]]:
    """Scenario -> the ``RTPU_CHAOS`` config dict (or None when the
    scenario injects no faults). Pure: same scenario+seed, same
    schedule — fault positions are HIT COUNTS (the chaos engine's
    replayable unit), not wall-clock times."""
    schedule: List[Dict[str, Any]] = []
    for a in scenario.actions:
        if a["kind"] == "controller_kill":
            schedule.append({"site": "serve.controller.tick",
                             "op": "kill", "at": int(a.get("tick", 5)),
                             "proc": "worker"})
        elif a["kind"] == "replica_kill":
            schedule.append({"site": "serve.replica.request",
                             "op": "kill",
                             "at": int(a.get("request", 50)),
                             "method": DEPLOYMENT_NAME,
                             "proc": "worker"})
    if not schedule:
        return None
    return {"seed": scenario.seed, "schedule": schedule}


# ---------------------------------------------------------------- builtins


def _flagship(seed: int = 411) -> Scenario:
    """The standing acceptance scenario (ROADMAP item 8): peak
    open-loop load with a diurnal ramp into a flash crowd, a rolling
    update launched mid-peak, and a chaos-seeded controller SIGKILL —
    gated on ZERO client-observed failed requests and an exact
    client/server reconciliation."""
    return Scenario(
        "flagship", seed=seed,
        description="rolling update + controller kill at peak load; "
                    "gate: 0 failed requests, exact reconciliation",
        phases=[
            {"name": "warmup", "duration_s": 2.0, "shape": "steady",
             "rps": 20},
            {"name": "ramp", "duration_s": 3.0, "shape": "diurnal",
             "min_rps": 20, "peak_rps": 70},
            {"name": "peak", "duration_s": 6.0, "shape": "flash_crowd",
             "base_rps": 50, "burst_rps": 90,
             "burst_start_frac": 0.2, "burst_frac": 0.5},
            {"name": "cooldown", "duration_s": 2.0, "shape": "steady",
             "rps": 15},
        ],
        actions=[
            # mid-peak redeploy: start-before-stop waves must absorb it
            {"kind": "rolling_update", "t_s": 6.0},
            # the controller dies at its 6th control-loop tick (~6 s
            # after serve.start) — recovery rides the journal while the
            # data plane serves from cached route tables
            {"kind": "controller_kill", "tick": 6},
        ],
        deployment={"num_replicas": 3, "max_concurrent_queries": 16,
                    "max_queued_requests": 96, "service_time_ms": 3.0},
        slo={"availability_target": 0.999, "latency_target_ms": 250.0},
    )


def _flash_crowd(seed: int = 902) -> Scenario:
    """Pure capacity story: a 4x flash crowd against a deployment
    sized for the baseline — the burst exceeds capacity, so admission
    control MUST shed (router assign timeout = the proxy's retriable
    503), sheds are counted and reconciled, and nothing may fail.
    Offered burst load ≈ 160 rps x ~95 ms mean service ≈ 15 concurrent
    vs 2 replicas x 4 slots = 8 — saturation by construction."""
    return Scenario(
        "flash-crowd", seed=seed,
        description="4x burst past capacity; sheds expected and "
                    "reconciled, 0 failed",
        phases=[
            {"name": "warmup", "duration_s": 2.0, "shape": "steady",
             "rps": 15},
            {"name": "crowd", "duration_s": 6.0, "shape": "flash_crowd",
             "base_rps": 30, "burst_rps": 160,
             "burst_start_frac": 0.3, "burst_frac": 0.4},
            {"name": "cooldown", "duration_s": 2.5, "shape": "steady",
             "rps": 10},
        ],
        actions=[],
        deployment={"num_replicas": 2, "max_concurrent_queries": 4,
                    "max_queued_requests": 8, "service_time_ms": 50.0,
                    "assign_timeout_s": 0.75},
        slo={"availability_target": 0.999, "latency_target_ms": 2000.0,
             "count_shed_as_bad": False},
        max_workers=48,
    )


def _replica_storm(seed: int = 737) -> Scenario:
    """Chaos-heavy variant: a replica SIGKILLed at an exact accepted-
    request count while traffic runs. Handle callers see the blast
    radius (requests in flight on the dead replica), so the SLO allows
    a small failure budget and the reconciler tolerates ledger records
    lost with the killed replica."""
    return Scenario(
        "replica-storm", seed=seed,
        description="replica SIGKILL under load; bounded blast radius",
        phases=[
            {"name": "warmup", "duration_s": 2.0, "shape": "steady",
             "rps": 20},
            {"name": "storm", "duration_s": 6.0, "shape": "steady",
             "rps": 80},
            {"name": "cooldown", "duration_s": 2.0, "shape": "steady",
             "rps": 15},
        ],
        # each replica dies at ITS 100th accepted request (the chaos
        # engine is per-process) — originals absorb ~160 requests each
        # over the storm, so the kills stagger through it while the
        # replacements stay under the threshold
        actions=[{"kind": "replica_kill", "request": 100}],
        deployment={"num_replicas": 3, "max_concurrent_queries": 16,
                    "max_queued_requests": 64, "service_time_ms": 3.0},
        slo={"availability_target": 0.98, "latency_target_ms": 500.0},
        tolerate_lost_server_records=True,
    )


def _diurnal_soak(seed: int = 128) -> Scenario:
    """Long soak (marked ``slow`` in tests): three diurnal cycles with
    a rolling update per trough and a controller kill mid-cycle."""
    cycles = []
    for i in range(3):
        cycles.append({"name": f"day{i}", "duration_s": 20.0,
                       "shape": "diurnal", "min_rps": 10,
                       "peak_rps": 60})
    return Scenario(
        "diurnal-soak", seed=seed,
        description="3 diurnal cycles, rolling update per trough, one "
                    "controller kill",
        phases=cycles,
        actions=[
            {"kind": "rolling_update", "t_s": 19.0},
            {"kind": "rolling_update", "t_s": 39.0},
            {"kind": "controller_kill", "tick": 30},
        ],
        deployment={"num_replicas": 3, "max_concurrent_queries": 16,
                    "max_queued_requests": 96, "service_time_ms": 3.0},
        slo={"availability_target": 0.999, "latency_target_ms": 250.0},
    )


def _llm_chat(seed: int = 623) -> Scenario:
    """LLM serving game day (ROADMAP item 1): streaming chat traffic
    with heavy-tail prompt AND output lengths (the arrival's bounded-
    Pareto ``size`` scales both), served by the continuous-batching
    engine (serve/llm) and consumed token by token — the SLO ledger
    counts tokens, not just requests, and reconciliation joins the
    client's per-request token counts against the engines' token
    ledgers. A rolling update mid-run proves KV-aware drain under
    load: every in-flight stream finishes on the draining replicas,
    zero sequences dropped.

    Tenancy is Zipf-skewed (6 tenants, skew 1.4) and every tenant's
    requests share a per-tenant system prompt (runner prepends it), so
    the radix prefix cache sees realistic shared-prefix traffic;
    reconciliation additionally checks that the engines' cache-hit
    token ledgers agree exactly with the client-observed prompt
    lengths (check C11)."""
    return Scenario(
        "llm-chat", seed=seed,
        description="streaming LLM chat, heavy-tail lengths, Zipf "
                    "shared-prefix tenants, rolling update mid-run; "
                    "per-token reconciliation, 0 failed",
        tenants=6, tenant_skew=1.4,
        phases=[
            {"name": "warmup", "duration_s": 2.0, "shape": "steady",
             "rps": 6},
            {"name": "chat", "duration_s": 8.0, "shape": "diurnal",
             "min_rps": 8, "peak_rps": 20},
            {"name": "cooldown", "duration_s": 2.0, "shape": "steady",
             "rps": 4},
        ],
        actions=[
            # mid-peak redeploy: draining replicas must finish their
            # in-flight decodes (KV-aware drain) while new replicas
            # pick up fresh streams
            {"kind": "rolling_update", "t_s": 5.0},
        ],
        deployment={
            "workload": "llm",
            "num_replicas": 2,
            "max_concurrent_queries": 32,
            "max_queued_requests": 64,
            "graceful_shutdown_timeout_s": 20.0,
            "assign_timeout_s": 15.0,
            # engine shape: small pool so occupancy moves, tiny
            # per-step delay so decode time is the workload
            "llm": {"model": "toy",
                    "model_config": {"per_seq_delay_s": 0.0005,
                                     "step_delay_s": 0.001},
                    "engine_config": {"max_running": 8,
                                      "max_waiting": 64,
                                      "num_blocks": 256,
                                      "block_size": 16,
                                      "max_seq_len": 512,
                                      "enable_prefix_cache": True}},
        },
        slo={"availability_target": 0.999,
             "latency_target_ms": 4000.0},
        max_workers=48,
    )


def _llm_chat_disagg(seed: int = 911) -> Scenario:
    """Disaggregated LLM serving game day: the same Zipf shared-prefix
    chat traffic as ``llm-chat``, but the fleet is split by role
    (``llm_roles``: 1 prefill + 2 decode over 3 replicas) so every
    admission is the router's two-hop path — ``__llm_prefill__`` on the
    prefill replica, KV pages shipped over a plasmax ring slot,
    ``__llm_adopt__`` rebinding them on a decode replica.  The rolling
    update mid-run retires replicas of BOTH roles while handoffs are in
    flight; greedy decode determinism + the re-prefill fallback mean
    reconciliation must still balance to the token (checks C10/C11, 0
    failed streams)."""
    return Scenario(
        "llm-chat-disagg", seed=seed,
        description="disaggregated (1 prefill + 2 decode) streaming LLM "
                    "chat, KV handoff per admission, rolling update "
                    "mid-run; per-token reconciliation, 0 failed",
        tenants=6, tenant_skew=1.4,
        phases=[
            {"name": "warmup", "duration_s": 2.0, "shape": "steady",
             "rps": 6},
            {"name": "chat", "duration_s": 8.0, "shape": "diurnal",
             "min_rps": 8, "peak_rps": 18},
            {"name": "cooldown", "duration_s": 2.0, "shape": "steady",
             "rps": 4},
        ],
        actions=[
            {"kind": "rolling_update", "t_s": 5.0},
        ],
        deployment={
            "workload": "llm",
            "num_replicas": 3,
            "llm_roles": {"prefill": 1, "decode": 2},
            "max_concurrent_queries": 32,
            "max_queued_requests": 64,
            "graceful_shutdown_timeout_s": 20.0,
            "assign_timeout_s": 15.0,
            "llm": {"model": "toy",
                    "model_config": {"per_seq_delay_s": 0.0005,
                                     "step_delay_s": 0.001},
                    "engine_config": {"max_running": 8,
                                      "max_waiting": 64,
                                      "num_blocks": 256,
                                      "block_size": 16,
                                      "max_seq_len": 512,
                                      "enable_prefix_cache": True}},
        },
        slo={"availability_target": 0.999,
             "latency_target_ms": 4000.0},
        max_workers=48,
    )


_BUILTIN = {
    "flagship": _flagship,
    "flash-crowd": _flash_crowd,
    "replica-storm": _replica_storm,
    "diurnal-soak": _diurnal_soak,
    "llm-chat": _llm_chat,
    "llm-chat-disagg": _llm_chat_disagg,
}


def builtin_scenarios() -> Dict[str, str]:
    """name -> one-line description of every builtin scenario."""
    return {name: fn().description for name, fn in _BUILTIN.items()}


def load_scenario(name_or_path: str,
                  seed: Optional[int] = None) -> Scenario:
    """Resolve a builtin scenario name or a JSON spec file; ``seed``
    overrides the spec's seed (a different seed is a different — but
    equally replayable — game day)."""
    if name_or_path in _BUILTIN:
        sc = (_BUILTIN[name_or_path](seed) if seed is not None
              else _BUILTIN[name_or_path]())
        return sc
    if os.path.exists(name_or_path):
        with open(name_or_path) as f:
            sc = Scenario.from_dict(json.load(f))
        if seed is not None:
            sc.seed = int(seed)
        return sc
    raise ValueError(
        f"unknown scenario {name_or_path!r}; builtins: "
        f"{', '.join(sorted(_BUILTIN))} (or a path to a JSON spec)")
