"""Game-day state in the GCS KV: the last published report (what the
dashboard panel and the ``ray_tpu_slo_*`` gauges render) and the
per-replica request ledgers that gracefully-stopped replicas flush so
a rolling update cannot erase the server-side half of the
reconciliation join (serve/_private/replica.py flushes on
``prepare_for_shutdown``).

Layout::

    @gameday/report                 -> JSON SLO report (no raw ledger)
    @gameday/ledger/<replica_name>  -> {"deployment", "replica",
                                        "records": [[rid, outcome,
                                                     dt_s], ...]}

Reads and writes are best-effort exactly like the serve journal: a KV
outage degrades observability, never the data plane.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu.gameday.store")

PREFIX = "@gameday/"
REPORT_KEY = PREFIX + "report"
LEDGER_PREFIX = PREFIX + "ledger/"
LLM_LEDGER_PREFIX = PREFIX + "llm-ledger/"


def _gcs_call(method: str, payload: Dict[str, Any], timeout: float = 10.0):
    from ray_tpu._private.worker import global_worker
    w = global_worker()
    return w.call_sync(w.gcs, method, payload, timeout=timeout)


def publish_report(report: Dict[str, Any]) -> bool:
    """Persist the latest game-day report (JSON — the dashboard actor
    and Prometheus exposition read it from another process)."""
    try:
        _gcs_call("kv_put", {"key": REPORT_KEY,
                             "value": json.dumps(report).encode()})
        return True
    except Exception:
        logger.warning("gameday: report publish failed", exc_info=True)
        return False


def load_report() -> Optional[Dict[str, Any]]:
    try:
        reply = _gcs_call("kv_get", {"key": REPORT_KEY})
        value = reply.get("value") if isinstance(reply, dict) else None
        if not value:
            return None
        if isinstance(value, str):
            value = value.encode()
        return json.loads(value)
    except Exception:
        return None


def flush_replica_ledger(replica_name: str, deployment: str,
                         records: List[Any],
                         truncated: bool = False) -> bool:
    """Called by a replica on graceful shutdown: persist its request
    ledger so reconciliation still sees requests served by replicas a
    rolling update has since retired."""
    if not records:
        return True
    try:
        _gcs_call("kv_put", {
            "key": LEDGER_PREFIX + replica_name,
            "value": json.dumps({
                "deployment": deployment,
                "replica": replica_name,
                "records": records,
                "truncated": bool(truncated),
            }).encode()})
        return True
    except Exception:
        logger.warning("gameday: ledger flush failed for %r",
                       replica_name, exc_info=True)
        return False


def load_flushed_ledgers() -> List[Dict[str, Any]]:
    """Every ledger flushed by retired replicas (reconciliation input)."""
    try:
        reply = _gcs_call("kv_get_prefix", {"prefix": LEDGER_PREFIX},
                          timeout=30.0)
    except Exception:
        return []
    out = []
    for _key, value in reply.get("items") or []:
        try:
            if isinstance(value, str):
                value = value.encode()
            out.append(json.loads(value))
        except Exception:
            continue
    return out


def flush_llm_ledger(replica_name: str, records: List[Any]) -> bool:
    """serve/llm: a replica retired by a rolling update flushes its
    per-request token ledger ((request_id, n_tokens, finish_reason)
    rows) so the per-token reconciliation join survives the drain."""
    if not records:
        return True
    try:
        _gcs_call("kv_put", {
            "key": LLM_LEDGER_PREFIX + replica_name,
            "value": json.dumps({"replica": replica_name,
                                 "records": records}).encode()})
        return True
    except Exception:
        logger.warning("gameday: llm ledger flush failed for %r",
                       replica_name, exc_info=True)
        return False


def load_flushed_llm_ledgers() -> List[Dict[str, Any]]:
    try:
        reply = _gcs_call("kv_get_prefix",
                          {"prefix": LLM_LEDGER_PREFIX}, timeout=30.0)
    except Exception:
        return []
    out = []
    for _key, value in reply.get("items") or []:
        try:
            if isinstance(value, str):
                value = value.encode()
            out.append(json.loads(value))
        except Exception:
            continue
    return out


def clear_ledgers() -> None:
    """Scenario start: drop stale ledgers so one game day never joins
    against another's records."""
    for prefix in (LEDGER_PREFIX, LLM_LEDGER_PREFIX):
        try:
            _gcs_call("kv_del", {"key": prefix, "prefix": True})
        except Exception:
            pass
