"""Game-day SLO harness — client-side truth for production claims.

Every number in PERF.md before this package was a server-side
microbenchmark run in isolation. A *game day* is the opposite: a
deterministic, replayable production-traffic scenario — open-loop load
with realistic shapes (diurnal ramp, flash crowd, heavy-tail request
sizes, tenant skew) composed with control-plane failures (the chaos
engine's seeded schedules, rolling updates, scale actions) — whose
verdict is computed purely from what *clients* observed, then
cross-checked against what the server-side observability plane
(replica ledgers, serve metrics, the state engine's task table,
Prometheus gauges) claims happened. Disagreement is a failure: the
observability plane itself is the thing under test.

Layers (docs/GAMEDAY.md):

* ``loadgen``   — seeded open-loop arrival schedules + the runner that
                  fires them at their scheduled instants (coordinated
                  omission cannot hide stalls: latency is measured from
                  the *intended* arrival, not the actual send).
* ``slo``       — client-side accounting: per-phase log-bucketed
                  latency histograms (p50/p99/p99.9), the
                  admitted/shed/failed ledger, error-budget burn.
* ``scenario``  — the replayable spec: load phases + timed actions +
                  the chaos schedule, all a pure function of
                  (scenario, seed): same seed ⇒ same schedule.
* ``runner``    — deploys the workload, drives the scenario end to
                  end, collects every server-side view.
* ``reconcile`` — the outside-in pass joining client and server views
                  per request id.
* ``store``     — last-report storage in the GCS KV (the dashboard's
                  game-day panel and the ``ray_tpu_slo_*`` gauges read
                  it).

Entry points: ``ray-tpu gameday run <scenario>``,
``_BENCH_GAMEDAY=1 python bench.py``, and the tier-1 flagship gate in
``tests/test_gameday.py``.
"""

from ray_tpu.gameday.loadgen import (Arrival, ArrivalSchedule,  # noqa: F401
                                     OpenLoopRunner, RequestRecord,
                                     build_schedule)
from ray_tpu.gameday.reconcile import reconcile  # noqa: F401
from ray_tpu.gameday.runner import GameDayResult, run_scenario  # noqa: F401
from ray_tpu.gameday.scenario import (Scenario, builtin_scenarios,  # noqa: F401
                                      chaos_config, load_scenario)
from ray_tpu.gameday.slo import (LatencyHistogram, build_report,  # noqa: F401
                                 error_budget_burn)
from ray_tpu.gameday.store import load_report, publish_report  # noqa: F401
