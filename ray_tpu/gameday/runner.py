"""Game-day execution: deploy, load, fault, collect, reconcile.

One ``run_scenario`` call is a complete game day:

1. Export the scenario's seeded chaos schedule (``RTPU_CHAOS``) and
   start a fresh cluster — the env rides process spawn, so the same
   schedule reaches the controller/replica workers deterministically.
2. Deploy the synthetic ``GameDay`` workload (configurable service
   time; request "work" multiplies it, so the heavy-tail sizes the
   load generator draws become heavy-tail service demand).
3. Fire the precomputed open-loop schedule through a deployment
   handle (request ids ride the ``__rtpu_request_id__`` kwarg into
   replica ledgers) while a background thread executes the timed
   actions (rolling updates, scale changes) and the chaos engine
   executes the seeded kills.
4. Quiesce, then collect every server-side view: live replica ledgers
   + counters, ledgers flushed by replicas retired mid-run, the
   controller's serve metrics, the state engine's task-table delta,
   a Prometheus ``/metrics`` scrape, and the chaos log.
5. Reconcile client vs server (``reconcile.py``), build the SLO
   report, publish it to the GCS KV (dashboard panel + ``ray_tpu_slo_*``
   gauges), and verify the published gauges actually appear.

A note on controller kills: the chaos engine is per-process, so a
``controller_kill`` schedule fires once per controller *incarnation* —
a long collection window may see the restarted controller die again at
the same tick count. That is by design (every incarnation replays the
same schedule); recovery is sub-second, every collection step retries
through restart windows, and reconciliation compares the deduplicated
(site, op, hit) set against the schedule.
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

from ray_tpu.gameday import store
from ray_tpu.gameday.loadgen import Arrival, OpenLoopRunner
from ray_tpu.gameday.reconcile import reconcile
from ray_tpu.gameday.scenario import (DEPLOYMENT_NAME, Scenario,
                                      chaos_config)
from ray_tpu.gameday.slo import build_report, ledger

logger = logging.getLogger("ray_tpu.gameday")

_REQUEST_TASK_NAME = "ReplicaActor.handle_request"


class GameDayApp:
    """The workload under test: a configurable-latency echo whose
    version is visible in responses (so a rolling update's overlap is
    observable) and whose per-request cost scales with the arrival's
    heavy-tail ``work`` factor."""

    def __init__(self, service_time_ms: float = 3.0):
        self._service_s = max(0.0, float(service_time_ms)) / 1e3
        self.version = 0

    def reconfigure(self, cfg):
        self.version = int(cfg.get("v", 0))
        if "service_time_ms" in cfg:
            self._service_s = max(0.0,
                                  float(cfg["service_time_ms"])) / 1e3

    def __call__(self, payload=None):
        work = 1.0
        if isinstance(payload, dict):
            try:
                work = float(payload.get("work", 1.0))
            except (TypeError, ValueError):
                work = 1.0
        time.sleep(self._service_s * min(max(work, 0.0), 50.0))
        return {"v": self.version}


class GameDayResult:
    def __init__(self, scenario: Scenario, records: List[Any],
                 report: Dict[str, Any], server_view: Dict[str, Any]):
        self.scenario = scenario
        self.records = records
        self.report = report
        self.server_view = server_view

    @property
    def reconciliation(self) -> Dict[str, Any]:
        return self.report.get("reconciliation") or {}

    @property
    def passed(self) -> bool:
        return bool(self.report.get("passed"))


def _bind_app(sc: Scenario, version: int):
    from ray_tpu import serve
    cfg = sc.deployment
    if cfg.get("workload") == "llm":
        # the stateful LLM workload (serve/llm): continuous batching +
        # paged KV + streaming; version rides user_config so a rolling
        # update replaces replicas exactly like the echo app's
        from ray_tpu.serve.llm import LLMServer
        llm = cfg.get("llm") or {}
        dep = serve.deployment(
            name=DEPLOYMENT_NAME,
            num_replicas=int(cfg.get("num_replicas", 2)),
            max_concurrent_queries=int(
                cfg.get("max_concurrent_queries", 32)),
            max_queued_requests=cfg.get("max_queued_requests"),
            user_config={"v": version},
            llm_roles=cfg.get("llm_roles"),
            graceful_shutdown_timeout_s=cfg.get(
                "graceful_shutdown_timeout_s", 20.0))(LLMServer)
        return dep.bind(llm.get("model", "toy"),
                        llm.get("model_config"),
                        llm.get("engine_config"))
    dep = serve.deployment(
        name=DEPLOYMENT_NAME,
        num_replicas=int(cfg.get("num_replicas", 3)),
        max_concurrent_queries=int(cfg.get("max_concurrent_queries", 16)),
        max_queued_requests=cfg.get("max_queued_requests"),
        user_config={"v": version,
                     "service_time_ms": cfg.get("service_time_ms", 3.0)},
        graceful_shutdown_timeout_s=cfg.get("graceful_shutdown_timeout_s",
                                            10.0))(GameDayApp)
    return dep.bind(cfg.get("service_time_ms", 3.0))


def _retry(fn, timeout: float = 30.0, default=None):
    """Run ``fn`` until it returns non-None, riding through controller
    restart windows (a killed controller answers again in <1 s)."""
    deadline = time.time() + timeout
    while True:
        try:
            out = fn()
            if out is not None:
                return out
        # errors are expected while the controller restarts; the
        # deadline below is the real failure signal
        except Exception:  # rtpulint: ignore[RTPU007]
            pass
        if time.time() >= deadline:
            return default
        time.sleep(0.4)


def _live_replica_handles() -> Dict[str, Any]:
    """Route-table replica ids -> actor handles (post-quiesce: the
    ready set IS the live set the controller aggregates metrics
    over)."""
    import ray_tpu
    from ray_tpu.actor import get_actor_by_id

    def table():
        ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
        _, t = ray_tpu.get(ctrl.get_route_table.remote(), timeout=5.0)
        return t

    t = _retry(table, timeout=30.0, default={}) or {}
    handles = {}
    for _dep, info in t.items():
        for hex_id in info.get("replicas") or []:
            try:
                handles[hex_id] = get_actor_by_id(hex_id)
            except Exception:
                logger.debug("gameday: replica %s in route table but "
                             "unresolvable (torn down mid-sweep?)",
                             hex_id, exc_info=True)
    return handles


def _all_alive_replica_handles() -> Dict[str, Any]:
    """EVERY alive ``SERVE_REPLICA::*`` actor — including replicas a
    rolling update is still draining (out of the route table but
    holding ledger records the reconciliation join needs; a kill-
    cycling controller can stretch a drain past collection time)."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.actor import ActorHandle
    from ray_tpu.common.ids import ActorID
    handles = {}
    try:
        w = global_worker()
        for a in w.call_sync(w.gcs, "list_actors", {}, timeout=10):
            if a.get("class_name") != "ReplicaActor" or \
                    a.get("state") != "ALIVE":
                continue
            try:
                h = ActorHandle(ActorID.from_hex(a["actor_id"]),
                                "ReplicaActor")
                if a.get("worker_address"):
                    h._worker_address = a["worker_address"]
                handles[a["actor_id"]] = h
            except Exception:
                logger.debug("gameday: could not build handle for "
                             "replica %s", a.get("actor_id"),
                             exc_info=True)
    except Exception:
        logger.warning("gameday: alive-replica sweep failed",
                       exc_info=True)
    return handles


def _task_counts() -> Dict[str, int]:
    """FINISHED/FAILED counts for the replica request method from one
    ``summarize_tasks`` RPC, plus the table's loss counters."""
    from ray_tpu.experimental.state import api as state
    s = state.summarize_tasks()
    fin = fail = 0
    for row in s.get("summary") or []:
        if row.get("name") == _REQUEST_TASK_NAME:
            by = row.get("by_state") or {}
            fin = int(by.get("FINISHED", 0))
            fail = int(by.get("FAILED", 0))
    return {"finished": fin, "failed": fail,
            "dropped": int(s.get("dropped", 0)),
            "events_dropped": int(s.get("events_dropped", 0))}


def _parse_serve_gauges(text: str) -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for m in re.finditer(
            r'ray_tpu_serve_(\w+)\{deployment="([^"]+)"\}\s+([0-9.eE+-]+)',
            text):
        out.setdefault(m.group(2), {})[m.group(1)] = float(m.group(3))
    return out


def _scrape_metrics(port: Optional[int]) -> Optional[str]:
    if port is None:
        return None
    try:
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=15).read().decode()
    except Exception:
        return None


def _quiesce(handles: Dict[str, Any], timeout: float = 25.0
             ) -> Dict[str, Dict[str, Any]]:
    """Wait until the controller's aggregated serve metrics agree with
    the replicas' own counters (totals stop moving once load stops and
    a metrics tick lands), then return the per-replica counters. On
    timeout returns the last direct read — reconciliation will surface
    the disagreement as a failed check rather than hiding it."""
    import ray_tpu
    from ray_tpu import serve
    deadline = time.time() + timeout
    reps: Dict[str, Dict[str, Any]] = {}
    while time.time() < deadline:
        reps = {}
        try:
            for hex_id, h in handles.items():
                reps[hex_id] = ray_tpu.get(h.get_metrics.remote(),
                                           timeout=5.0)
        except Exception:
            time.sleep(0.5)
            continue
        sm = serve.metrics()
        if sm:
            sum_req = sum(m.get("total_requests", 0)
                          for m in reps.values())
            sum_shed = sum(m.get("total_shed", 0) for m in reps.values())
            agg_req = sum(d.get("requests_total", 0) for d in sm.values())
            agg_shed = sum(d.get("shed_total", 0) for d in sm.values())
            if sum_req == agg_req and sum_shed == agg_shed:
                return reps
        time.sleep(0.5)
    return reps


def run_scenario(scenario: Scenario, *, scale: float = 1.0,
                 num_cpus: int = 8, publish: bool = True,
                 dashboard_port: Optional[int] = 18470,
                 request_timeout_s: float = 30.0) -> GameDayResult:
    """Run one game day end to end on a fresh local cluster it owns
    (the chaos schedule must ride the env into every spawned process,
    so the cluster cannot pre-exist the scenario)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private import chaos
    from ray_tpu.serve._private.router import is_overload_error

    if ray_tpu.is_initialized():
        raise RuntimeError(
            "gameday.run_scenario needs to own the cluster (the seeded "
            "chaos schedule rides process-spawn env); call it before "
            "ray_tpu.init, or after ray_tpu.shutdown()")

    schedule = scenario.arrival_schedule(scale)
    actions = scenario.timed_actions(scale)
    chaos_cfg = chaos_config(scenario)

    prev_env = {k: os.environ.get(k)
                for k in ("RTPU_CHAOS", "RTPU_CHAOS_LOG",
                          "RTPU_ACTOR_TASK_EVENTS",
                          "RTPU_TRACE_SAMPLE")}
    chaos_log = os.path.join(tempfile.mkdtemp(prefix="rtpu-gameday-"),
                             "chaos.jsonl")
    if chaos_cfg is not None:
        os.environ["RTPU_CHAOS"] = json.dumps(chaos_cfg)
        os.environ["RTPU_CHAOS_LOG"] = chaos_log
    else:
        os.environ.pop("RTPU_CHAOS", None)
    # the state-engine cross-check (reconcile C6) needs the task table
    # to see replica request tasks — actor-call events are opt-in
    os.environ["RTPU_ACTOR_TASK_EVENTS"] = "1"
    # a game day traces EVERY request (default head sampling is 10%):
    # the trace-completeness check (C9) and the p99 critical-path
    # aggregation need full span trees, and the run is bounded anyway
    os.environ.setdefault("RTPU_TRACE_SAMPLE", "1.0")
    from ray_tpu._private import tracing as _tracing
    _tracing.refresh()

    server_view: Dict[str, Any] = {"chaos_expected": chaos_cfg}
    t_setup = time.time()
    try:
        ray_tpu.init(num_cpus=num_cpus,
                     object_store_memory=256 * 1024 * 1024,
                     _system_config={"prestart_workers": False})
        # a previous cluster in this process may have left the global
        # serve router pinned to its (now dead) controller — drop it so
        # handles resolve against THIS cluster
        from ray_tpu.serve.handle import _reset_router
        _reset_router()
        store.clear_ledgers()
        dash_port = None
        if dashboard_port is not None:
            try:
                from ray_tpu.dashboard.dashboard import start_dashboard
                dash_port = start_dashboard(port=dashboard_port)
            except Exception:
                logger.warning("gameday: dashboard unavailable; "
                               "skipping the Prometheus cross-check")

        h = serve.run(_bind_app(scenario, 1), http_port=None,
                      _blocking_timeout=120.0)

        # warmup: touch every replica a few times so compile/startup
        # cost never lands inside a measured phase; warmup ids are
        # visible in replica ledgers (harmless to every join)
        is_llm = scenario.deployment.get("workload") == "llm"
        warm_payload = ({"tokens": [1, 2, 3], "max_new_tokens": 2}
                        if is_llm else {"work": 1.0})
        warm = 4 * int(scenario.deployment.get("num_replicas", 3))
        for i in range(warm):
            ray_tpu.get(h.remote(
                warm_payload,
                __rtpu_request_id__=f"warmup-{scenario.seed}-{i}"),
                timeout=60.0)
        time.sleep(1.5)  # task-event flush (0.5 s batches) settles
        task_base = _retry(_task_counts, timeout=15.0,
                           default={"finished": 0, "failed": 0,
                                    "dropped": 0, "events_dropped": 0})

        # ---- timed actions on their own clock ----
        action_errors: List[str] = []
        load_t0 = time.time() + 0.25  # shared epoch for load + actions

        def run_actions():
            ver = 1
            for a in actions:
                delay = load_t0 + a["t_s"] - time.time()
                if delay > 0:
                    time.sleep(delay)
                try:
                    if a["kind"] == "rolling_update":
                        ver += 1
                        serve.run(_bind_app(scenario, ver),
                                  http_port=None,
                                  _blocking_timeout=120.0)
                    elif a["kind"] == "scale":
                        sc2 = Scenario.from_dict(scenario.to_dict())
                        sc2.deployment["num_replicas"] = int(
                            a["num_replicas"])
                        serve.run(_bind_app(sc2, ver), http_port=None,
                                  _blocking_timeout=120.0)
                except Exception as e:  # noqa: BLE001 — surfaced below
                    action_errors.append(
                        f"{a['kind']}@{a['t_s']}s: "
                        f"{type(e).__name__}: {e}")

        action_thread = threading.Thread(target=run_actions, daemon=True)
        action_thread.start()

        # ---- open-loop load ----
        # requests go through the shared Router directly so admission
        # control is visible: a replica that sheds raises
        # ReplicaOverloadedError (server-side shed, ledgered), and an
        # assign that can't place the request within assign_timeout_s
        # means every replica is saturated — the same condition the
        # HTTP proxy maps to a retriable 503 (client-side shed, never
        # reached a replica)
        from ray_tpu import exceptions as rexc
        from ray_tpu.serve._private.replica import REQUEST_ID_KWARG
        from ray_tpu.serve.handle import _get_router
        router = _get_router(ray_tpu.get_actor("SERVE_CONTROLLER"))
        assign_timeout = float(scenario.deployment.get(
            "assign_timeout_s", 30.0))

        def send(arrival: Arrival):
            # retry a request that landed on a dead replica on another
            # one, same id — the HTTP proxy's idempotent-GET behavior
            # (backoff + membership refresh); a retried request is ONE
            # logical request in every ledger. Once every known replica
            # is excluded, assign blocks until the controller publishes
            # replacements — a full-fleet wipeout is ridden out, not
            # failed, as long as recovery lands inside assign_timeout.
            exclude = None
            last: Optional[BaseException] = None
            for attempt in range(5):
                ref, release, replica = router.assign_request(
                    DEPLOYMENT_NAME, "__call__",
                    ({"work": arrival.size},),
                    {REQUEST_ID_KWARG: arrival.rid},
                    timeout=assign_timeout, exclude=exclude)
                try:
                    ray_tpu.get(ref, timeout=request_timeout_s)
                    return
                except (rexc.ActorDiedError,
                        rexc.ActorUnavailableError) as e:
                    last = e
                    exclude = (exclude or set()) | {replica._id_hex}
                    time.sleep(0.3 * (attempt + 1))
                    router.force_refresh()
                finally:
                    release()
            raise last

        def classify(err: BaseException) -> str:
            if is_overload_error(err):
                return "shed"
            # GetTimeoutError (accepted but slow) subclasses
            # TimeoutError — it is a failure, not a shed; only the
            # router's assign timeout (bare TimeoutError) is admission
            # shedding
            if isinstance(err, TimeoutError) and \
                    not isinstance(err, rexc.GetTimeoutError):
                return "shed"
            return "failed"

        # ---- LLM workload: streaming sends, counted per token ----
        # every request opens a stream and consumes it chunk by chunk;
        # the SLO ledger gets (rid -> tokens received, first-token
        # time), and reconciliation joins those counts against the
        # engines' token ledgers. A broken stream is retried WHOLE
        # (fresh generation, same rid — one logical request) or fails
        # cleanly; a partially-read stream never counts as ok.
        import random as _random

        from ray_tpu.serve.exceptions import StreamBrokenError
        token_counts: Dict[str, int] = {}
        first_token_at: Dict[str, float] = {}
        prompt_lens: Dict[str, int] = {}
        tc_lock = threading.Lock()

        _SYS_PROMPT_TOKENS = 32  # 2 full pages at the engine's bs=16

        def _tenant_prefix(tenant: str) -> List[int]:
            # every tenant's requests share a deterministic "system
            # prompt": with Zipf-skewed tenancy the hot tenants' traffic
            # is exactly the shared-prefix shape the radix prefix cache
            # exists for (32 tokens = 2 full pages at block_size 16)
            rng = _random.Random(f"sys:{tenant}")
            return [rng.randrange(256)
                    for _ in range(_SYS_PROMPT_TOKENS)]

        def _llm_payload(arrival: Arrival) -> Dict[str, Any]:
            # heavy-tail prompt AND output lengths from the arrival's
            # bounded-Pareto size — deterministic per request id
            rng = _random.Random(f"llm:{arrival.rid}")
            plen = max(2, min(48, int(2 + arrival.size * 3)))
            ntok = max(1, min(40, int(1 + arrival.size * 2)))
            tokens = _tenant_prefix(arrival.tenant) + \
                [rng.randrange(256) for _ in range(plen)]
            with tc_lock:
                prompt_lens[arrival.rid] = len(tokens)
            return {"tokens": tokens, "max_new_tokens": ntok}

        def send_llm(arrival: Arrival):
            payload = _llm_payload(arrival)
            last: Optional[BaseException] = None
            for attempt in range(3):
                stream = router.open_stream(
                    DEPLOYMENT_NAME, payload, request_id=arrival.rid,
                    assign_timeout=assign_timeout)
                n, t_first = 0, None
                try:
                    for ch in stream:
                        if t_first is None and ch.get("tokens"):
                            t_first = time.time()
                        n += len(ch.get("tokens") or ())
                    with tc_lock:
                        token_counts[arrival.rid] = n
                        if t_first is not None:
                            first_token_at[arrival.rid] = t_first
                    return
                except StreamBrokenError as e:
                    last = e
                    time.sleep(0.3 * (attempt + 1))
                    router.force_refresh()
            raise last

        if is_llm:
            send = send_llm

        lg = OpenLoopRunner(schedule, send, classify,
                            max_workers=scenario.max_workers)
        delay = load_t0 - time.time()
        if delay > 0:
            time.sleep(delay)
        records = lg.run()
        action_thread.join(timeout=180.0)

        # ---- collect the server's story ----
        time.sleep(1.5)  # final task-event batch flushes
        routed = _live_replica_handles()
        replica_metrics_raw = _quiesce(routed)
        # ledgers come from EVERY alive replica (a draining old-version
        # replica is out of the route table but still holds its half of
        # the join), merged with the ledgers retired replicas flushed
        # to the KV; per replica, the larger snapshot wins (the ledger
        # only grows, and double-counting one replica would read as
        # duplicate completions)
        by_name: Dict[str, Dict[str, Any]] = {}
        replica_metrics: Dict[str, Dict[str, Any]] = {}
        for hex_id, handle in _all_alive_replica_handles().items():
            try:
                led = ray_tpu.get(handle.get_request_log.remote(),
                                  timeout=10.0)
                led["live"] = hex_id in routed
                by_name[led["replica"]] = led
                m = replica_metrics_raw.get(hex_id)
                if m is not None:
                    replica_metrics[led["replica"]] = m
            except Exception:
                logger.warning("gameday: replica %s ledger read failed",
                               hex_id[:8], exc_info=True)
        for led in store.load_flushed_ledgers():
            have = by_name.get(led.get("replica"))
            if have is None:
                led["live"] = False
                by_name[led["replica"]] = led
            elif len(led.get("records") or ()) > \
                    len(have.get("records") or ()):
                led["live"] = have["live"]
                by_name[led["replica"]] = led
        replica_ledgers = list(by_name.values())

        # LLM workload: collect every alive engine's metrics + token
        # ledger (counter-free RPC), merged with the ledgers retired
        # replicas flushed on drain — the server half of the per-token
        # join
        llm_ledgers: List[Dict[str, Any]] = []
        llm_metrics: Dict[str, Any] = {}
        if is_llm:
            for hex_id, handle in _all_alive_replica_handles().items():
                try:
                    st = ray_tpu.get(handle.get_llm_state.remote(),
                                     timeout=10.0)
                except Exception:
                    logger.debug("gameday: get_llm_state from replica "
                                 "%s failed (drained mid-grade?)",
                                 hex_id, exc_info=True)
                    continue
                if st:
                    llm_metrics[hex_id] = {
                        k: v for k, v in st.items()
                        if k != "token_ledger"}
                    llm_ledgers.append(
                        {"replica": hex_id,
                         "records": st.get("token_ledger") or []})
            llm_ledgers.extend(store.load_flushed_llm_ledgers())

        serve_metrics = _retry(lambda: serve.metrics() or None,
                               timeout=20.0, default={})
        task_now = _retry(_task_counts, timeout=15.0, default=None)
        task_delta = None
        if task_now is not None and task_base is not None:
            task_delta = {
                "finished": task_now["finished"] - task_base["finished"],
                "failed": task_now["failed"] - task_base["failed"],
                "dropped": task_now["dropped"],
                "events_dropped": task_now["events_dropped"],
            }
        prom_text = _scrape_metrics(dash_port)
        fired = chaos.read_log(chaos_log) if chaos_cfg else []
        # dedup: every controller incarnation replays the same
        # schedule, so repeated (site, op, n) entries are one fault
        seen, fired_unique = set(), []
        for r in fired:
            key = (r.get("site"), r.get("op"), r.get("n"))
            if key not in seen:
                seen.add(key)
                fired_unique.append({"site": r.get("site"),
                                     "op": r.get("op"),
                                     "n": r.get("n")})

        # distributed traces of the sampled admitted cohort: the
        # tracing plane must hold a complete span tree for every
        # request it claims to sample (reconcile C9) — and the tail's
        # traces feed the critical-path aggregation below
        from ray_tpu._private import tracing
        from ray_tpu.experimental.state import api as state_api
        ok_records = [r for r in records if r.outcome == "ok"]
        sampled = [r.rid for r in ok_records if tracing.sampled(r.rid)]
        trace_cap = int(os.environ.get("RTPU_GAMEDAY_TRACE_MAX", 500))
        if len(sampled) > trace_cap:
            logger.info("gameday: checking %d of %d sampled traces "
                        "(RTPU_GAMEDAY_TRACE_MAX)", trace_cap,
                        len(sampled))
            sampled = sampled[:trace_cap]
        traces: Dict[str, Any] = {}
        traces_lossy = False

        def fetch_traces(rids):
            nonlocal traces_lossy
            for rid in rids:
                try:
                    doc = state_api.get_trace(rid)
                except Exception:
                    logger.debug("gameday: trace fetch for %s failed",
                                 rid, exc_info=True)
                    traces_lossy = True
                    continue
                if doc.get("dropped_spans"):
                    traces_lossy = True
                if doc.get("spans"):
                    traces[rid] = doc["spans"]

        fetch_traces(sampled)
        # one settle pass: the last requests' spans may still be inside
        # a 0.5 s flush tick (or a draining replica's shutdown flush)
        from ray_tpu._private.tracing import tree_complete
        laggards = [rid for rid in sampled
                    if rid not in traces
                    or not tree_complete(traces[rid])[0]]
        if laggards:
            time.sleep(1.2)
            fetch_traces(laggards)

        server_view.update({
            "replica_ledgers": replica_ledgers,
            "replica_metrics": replica_metrics,
            "serve_metrics": serve_metrics,
            "task_delta": task_delta,
            "prometheus": ({"serve": _parse_serve_gauges(prom_text)}
                           if prom_text is not None else {}),
            "chaos_fired": fired_unique,
            "traces": traces,
            "traces_sampled": sampled,
            "traces_lossy": traces_lossy,
        })
        if is_llm:
            with tc_lock:
                server_view["llm_client_tokens"] = dict(token_counts)
                server_view["llm_client_prompts"] = dict(prompt_lens)
            server_view["llm_ledgers"] = llm_ledgers
            server_view["llm_metrics"] = llm_metrics

        # ---- grade + publish ----
        # split client sheds: a replica-shed has a server ledger record
        # to join against; an admission-shed (router assign timeout —
        # every replica saturated) never reached a replica, so the
        # reconciler checks its ABSENCE from server records instead
        client_ledger = ledger(records)
        unplaced = {r.rid for r in records
                    if r.outcome == "shed" and r.error
                    and r.error.startswith("TimeoutError")}
        client_ledger["unplaced"] = sorted(unplaced)
        client_ledger["shed"] = [rid for rid in client_ledger["shed"]
                                 if rid not in unplaced]
        recon = reconcile(scenario, client_ledger, server_view)
        report = build_report(
            records, scenario=scenario.name, seed=scenario.seed,
            availability_target=scenario.slo["availability_target"],
            latency_target_ms=scenario.slo.get("latency_target_ms"),
            count_shed_as_bad=scenario.slo.get("count_shed_as_bad",
                                               False),
            duration_s=schedule.duration_s)
        report["scale"] = scale
        report["setup_s"] = round(load_t0 - t_setup, 2)
        if is_llm:
            # per-token SLO accounting: throughput + open-loop TTFT
            # (first token time measured against the SCHEDULED arrival
            # — a stalled engine charges every token it delayed)
            with tc_lock:
                tok_total = sum(token_counts.values())
                ttfts = sorted(
                    max(0.0, t1 - (load_t0 + a.t))
                    for a in schedule.arrivals
                    for t1 in (first_token_at.get(a.rid),)
                    if t1 is not None)

            def _q(vals, frac):
                return (round(vals[min(len(vals) - 1,
                                       int(frac * len(vals)))] * 1e3, 3)
                        if vals else 0.0)

            report["llm"] = {
                "tokens_total": tok_total,
                "tokens_per_s": round(
                    tok_total / max(schedule.duration_s, 1e-9), 3),
                "requests_with_tokens": len(token_counts),
                "ttft_p50_ms": _q(ttfts, 0.50),
                "ttft_p99_ms": _q(ttfts, 0.99),
            }
        report["actions"] = actions
        report["action_errors"] = action_errors
        report["chaos_fired"] = fired_unique
        report["reconciliation"] = recon
        # where does the tail spend its time? aggregate critical path
        # over the p99 cohort's traces (ISSUE 13: latency attribution
        # before optimization)
        p99_ms = report.get("overall", {}).get("p99_ms") or 0.0
        cohort = [traces[r.rid] for r in ok_records
                  if r.rid in traces and r.latency_s * 1e3 >= p99_ms]
        report["critical_path_p99"] = \
            tracing.aggregate_critical_path(cohort[:50])
        burn = report["slo"]["availability_burn"]
        report["passed"] = (recon["ok"] and not action_errors
                            and 0.0 <= burn <= 1.0)
        report["ts"] = time.time()
        if publish:
            publish_ok = store.publish_report(report)
            if publish_ok and dash_port is not None:
                # the publish itself is under test: the SLO gauges must
                # round-trip through the KV into /metrics
                text = _scrape_metrics(dash_port)
                publish_ok = bool(text) and "ray_tpu_slo_" in text
            report["slo_gauges_published"] = bool(publish_ok)
        return GameDayResult(scenario, records, report, server_view)
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        from ray_tpu._private import tracing as _tracing
        _tracing.refresh()
        chaos.clear()
