"""Client-side SLO accounting.

Everything here is computed from ``RequestRecord``s alone — the load
generator's view, never the server's. The report a game day publishes
is therefore the number a *user* would have measured, and the server's
own telemetry has to reconcile against it (``reconcile.py``), not the
other way around.

Latency quantiles come from a log-bucketed histogram (bounded memory,
mergeable, ~2.5% bucket resolution) over open-loop latencies — the
time from each request's *scheduled* arrival to completion, so stalls
charge every request they delayed.

Error-budget burn follows the SRE definition: with availability target
``a`` over a window, the budget is the ``1 - a`` fraction of requests
allowed to fail; burn is the fraction of that budget actually spent,
normalized so 1.0 = exactly exhausted. A separate latency budget burns
on requests over ``latency_target_ms``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional

_BUCKET_BASE_S = 1e-4      # 0.1 ms floor
_BUCKET_GROWTH = 1.025     # ~2.5% relative resolution per bucket
_BUCKET_COUNT = 640        # covers 0.1 ms .. ~700 s


class LatencyHistogram:
    """Log-bucketed latency histogram: O(1) record, bounded memory,
    mergeable across phases/clients, quantiles within one bucket."""

    __slots__ = ("counts", "n", "max_s", "sum_s")

    def __init__(self):
        self.counts = [0] * _BUCKET_COUNT
        self.n = 0
        self.max_s = 0.0
        self.sum_s = 0.0

    def _bucket(self, v: float) -> int:
        if v <= _BUCKET_BASE_S:
            return 0
        b = int(math.log(v / _BUCKET_BASE_S) / math.log(_BUCKET_GROWTH))
        return min(b, _BUCKET_COUNT - 1)

    def record(self, v: float):
        self.counts[self._bucket(v)] += 1
        self.n += 1
        self.sum_s += v
        if v > self.max_s:
            self.max_s = v

    def merge(self, other: "LatencyHistogram"):
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.n += other.n
        self.sum_s += other.sum_s
        self.max_s = max(self.max_s, other.max_s)

    def quantile(self, q: float) -> float:
        """Upper edge of the bucket holding the q-th sample (a latency
        SLO wants "no worse than", so the conservative edge)."""
        if self.n == 0:
            return 0.0
        rank = min(self.n - 1, int(q * self.n))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen > rank:
                return min(_BUCKET_BASE_S * _BUCKET_GROWTH ** (i + 1),
                           self.max_s)
        return self.max_s

    def mean(self) -> float:
        return self.sum_s / self.n if self.n else 0.0


def error_budget_burn(total: int, bad: int, target: float) -> float:
    """Fraction of the error budget spent (1.0 = exhausted). A target
    of 1.0 (zero allowed failures) burns infinitely on the first bad
    request — reported as ``inf``."""
    if total <= 0 or bad <= 0:
        return 0.0
    allowed = (1.0 - target) * total
    if allowed <= 0:
        return float("inf")
    return bad / allowed


def _phase_stats(records, hist: LatencyHistogram) -> Dict[str, Any]:
    ok = sum(1 for r in records if r.outcome == "ok")
    shed = sum(1 for r in records if r.outcome == "shed")
    failed = sum(1 for r in records if r.outcome == "failed")
    return {
        "total": len(records),
        "admitted": ok,
        "shed": shed,
        "failed": failed,
        "p50_ms": round(hist.quantile(0.50) * 1e3, 3),
        "p99_ms": round(hist.quantile(0.99) * 1e3, 3),
        "p999_ms": round(hist.quantile(0.999) * 1e3, 3),
        "max_ms": round(hist.max_s * 1e3, 3),
        "mean_ms": round(hist.mean() * 1e3, 3),
    }


def build_report(records: Iterable[Any], *,
                 scenario: str = "gameday", seed: int = 0,
                 availability_target: float = 0.999,
                 latency_target_ms: Optional[float] = None,
                 count_shed_as_bad: bool = False,
                 duration_s: float = 0.0) -> Dict[str, Any]:
    """Records -> the client-side SLO report (JSON-serializable).

    ``count_shed_as_bad`` decides whether load shedding (retriable 503)
    burns availability budget: a capacity game day says no (shedding IS
    the designed behavior under overload), a strict availability SLO
    says yes.
    """
    records = list(records)
    by_phase: Dict[str, List[Any]] = {}
    phase_hists: Dict[str, LatencyHistogram] = {}
    overall_hist = LatencyHistogram()
    per_tenant: Dict[str, int] = {}
    for r in records:
        by_phase.setdefault(r.phase, []).append(r)
        h = phase_hists.get(r.phase)
        if h is None:
            h = phase_hists[r.phase] = LatencyHistogram()
        if r.outcome == "ok":
            h.record(r.latency_s)
            overall_hist.record(r.latency_s)
        per_tenant[r.tenant] = per_tenant.get(r.tenant, 0) + 1

    phases = {name: _phase_stats(rs, phase_hists[name])
              for name, rs in by_phase.items()}
    overall = _phase_stats(records, overall_hist)

    bad = overall["failed"] + (overall["shed"] if count_shed_as_bad
                               else 0)
    avail_burn = error_budget_burn(overall["total"], bad,
                                   availability_target)
    report: Dict[str, Any] = {
        "scenario": scenario,
        "seed": seed,
        "duration_s": round(duration_s, 3),
        "phases": phases,
        "overall": overall,
        "tenants": per_tenant,
        "slo": {
            "availability_target": availability_target,
            "count_shed_as_bad": count_shed_as_bad,
            "availability_burn": (avail_burn if math.isfinite(avail_burn)
                                  else -1.0),
        },
    }
    # the worst admitted requests, each linked to its distributed trace
    # (trace id == request id end to end), so the SLO report's tail is
    # one `ray-tpu trace critical-path` away from an explanation
    try:
        from ray_tpu._private import tracing
        admitted = [r for r in records if r.outcome == "ok"]
        admitted.sort(key=lambda r: -r.latency_s)
        report["slowest"] = [
            {"rid": r.rid, "latency_ms": round(r.latency_s * 1e3, 3),
             "phase": r.phase, "trace_id": r.rid,
             "trace_sampled": tracing.sampled(r.rid)}
            for r in admitted[:10]]
    except Exception:
        report["slowest"] = []

    if latency_target_ms is not None:
        slow = sum(1 for r in records if r.outcome == "ok"
                   and r.latency_s * 1e3 > latency_target_ms)
        report["slo"]["latency_target_ms"] = latency_target_ms
        report["slo"]["latency_over_target"] = slow
        lat_burn = error_budget_burn(overall["admitted"], slow,
                                     availability_target)
        report["slo"]["latency_burn"] = (lat_burn
                                         if math.isfinite(lat_burn)
                                         else -1.0)
    return report


def ledger(records: Iterable[Any]) -> Dict[str, List[str]]:
    """The client ledger: request ids grouped by observed outcome —
    what the reconciliation pass joins against server records."""
    out: Dict[str, List[str]] = {"ok": [], "shed": [], "failed": []}
    for r in records:
        out[r.outcome].append(r.rid)
    return out
