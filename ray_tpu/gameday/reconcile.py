"""Outside-in reconciliation: the client ledger vs the server's story.

The game-day verdict is only trustworthy if the observability plane
agrees with it — and the observability plane is only trustworthy if it
agrees with the clients. This pass joins the two views per request id
(the id the client generated, propagated proxy→router→replica and
recorded in each replica's request ledger) and cross-checks every
aggregate the server publishes:

  C1 completed-join   every client-observed success has a matching
                      "ok" record in some replica ledger (live or
                      flushed-on-drain)
  C2 admitted=completed  the match is exact: no success double-served,
                      no server completion for a request the client
                      saw fail (an "unexplained outcome")
  C3 shed-listed      every client-observed shed appears as a shed
                      record server-side
  C4 replica-totals   each live replica's counters equal its own
                      ledger (the counters feeding routing/autoscaling
                      can't drift from the per-request truth)
  C5 serve-metrics    the controller's aggregated serve metrics equal
                      the sum of live replica counters
  C6 state-engine     the GCS task table's FINISHED/FAILED deltas for
                      the replica request method equal the client's
                      ok/shed+failed counts
  C7 prometheus       the scraped ``ray_tpu_serve_*`` gauges equal the
                      controller metrics they claim to export
  C8 chaos-replay     the faults that actually fired are exactly the
                      scenario's seeded schedule (site/op/hit-count)
  C9 trace-complete   every head-SAMPLED admitted request has a stored
                      distributed trace forming a complete span tree
                      (>=1 root, no orphan spans) — the tracing plane
                      must cover exactly what it claims to sample
  C10 llm-tokens      (LLM workload only) every client-ok request's
                      RECEIVED token count equals the generating
                      engine's token-ledger entry for that request id
                      — streaming may not drop or invent tokens, and a
                      rolling update may not lose the ledger

Any disagreement fails the check (and, in tier-1, the test) — except
where the scenario explicitly tolerates records lost with SIGKILLed
replicas (``tolerate_lost_server_records``).

LLM note: one logical LLM request is one ``__llm_open__`` plus N
cursor polls on the replica request path, so the task-table request
count (C6) cannot equal the client's logical-request count — C6 is
reported informationally for the LLM workload and C10 carries the
exactness burden instead.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set


def _check(name: str, ok: bool, detail: str) -> Dict[str, Any]:
    return {"name": name, "ok": bool(ok), "detail": detail}


def _ledger_rids(ledgers: List[Dict[str, Any]], outcome: str) -> List[str]:
    out = []
    for led in ledgers:
        for rec in led.get("records") or []:
            rid, out_come = rec[0], rec[1]
            if rid is not None and out_come == outcome:
                out.append(rid)
    return out


def reconcile(scenario, client_ledger: Dict[str, List[str]],
              server_view: Dict[str, Any]) -> Dict[str, Any]:
    """client ledger ({"ok"/"shed"/"failed": [rids]}) + collected
    server view -> reconciliation report. ``server_view`` keys (all
    optional — absent sources are reported as skipped, not passed):

      replica_ledgers   [{deployment, replica, live, records}]
      replica_metrics   {replica_name: get_metrics() dict}
      serve_metrics     serve.metrics() snapshot (quiesced)
      task_delta        {finished, failed, dropped, events_dropped}
      prometheus        {serve: {deployment: {metric: value}}}
      chaos_fired       chaos.read_log records
      chaos_expected    the scenario's chaos_config schedule (or None)
      traces            {rid: [spans]} for the sampled admitted cohort
      traces_sampled    the rids the runner expected traces for
      traces_lossy      True when the trace table reported evictions
                        (completeness then can't be graded exactly)
    """
    checks: List[Dict[str, Any]] = []
    tolerate = bool(getattr(scenario, "tolerate_lost_server_records",
                            False))
    is_llm = bool((getattr(scenario, "deployment", None) or {}).get(
        "workload") == "llm")

    ok_rids = set(client_ledger.get("ok") or [])
    shed_rids = set(client_ledger.get("shed") or [])
    failed_rids = set(client_ledger.get("failed") or [])
    # admission sheds: the router couldn't place them (every replica
    # saturated) — they never reached a replica, so the server must
    # have NO record of them
    unplaced_rids = set(client_ledger.get("unplaced") or [])

    ledgers = server_view.get("replica_ledgers") or []
    server_ok_list = _ledger_rids(ledgers, "ok")
    server_ok: Set[str] = set(server_ok_list)
    server_shed: Set[str] = set(_ledger_rids(ledgers, "shed"))

    # C1: every client success is known to a replica ledger
    missing = ok_rids - server_ok
    if tolerate and missing:
        checks.append(_check(
            "completed-join", True,
            f"{len(missing)} client-ok records lost with SIGKILLed "
            f"replicas (tolerated by scenario)"))
    else:
        checks.append(_check(
            "completed-join", not missing,
            f"{len(ok_rids)} client-ok, {len(missing)} missing from "
            f"replica ledgers" + (f" e.g. {sorted(missing)[:3]}"
                                  if missing else "")))

    # C2: exact — no double completion, no unexplained outcome, and
    # nothing the router never placed shows up server-side
    dupes = len(server_ok_list) - len(server_ok)
    unexplained = server_ok & (shed_rids | failed_rids)
    ghost = unplaced_rids & (server_ok | server_shed)
    checks.append(_check(
        "admitted-equals-completed",
        dupes == 0 and not unexplained and not ghost,
        f"{dupes} duplicate completions, {len(unexplained)} requests "
        f"completed server-side but shed/failed client-side, "
        f"{len(ghost)} never-placed requests with server records"))

    # C3: sheds the client saw are listed as sheds server-side
    unlisted = shed_rids - server_shed
    if tolerate and unlisted:
        checks.append(_check(
            "shed-listed", True,
            f"{len(unlisted)} shed records lost with SIGKILLed "
            f"replicas (tolerated)"))
    else:
        checks.append(_check(
            "shed-listed", not unlisted,
            f"{len(shed_rids)} client-shed, {len(unlisted)} not "
            f"listed as shed server-side"))

    # C4: each live replica's counters == its own ledger
    rep_metrics = server_view.get("replica_metrics") or {}
    by_name = {led.get("replica"): led for led in ledgers
               if led.get("live")}
    bad = []
    for name, m in rep_metrics.items():
        led = by_name.get(name)
        if led is None:
            continue
        recs = led.get("records") or []
        if led.get("truncated"):
            bad.append(f"{name}: ledger truncated (raise "
                       f"RTPU_SERVE_REQUEST_LOG_MAX)")
            continue
        # "prefill" rows are the disagg two-hop's internal first hop —
        # admitted work (the counter saw it) but not a completion
        admitted = sum(1 for r in recs
                       if r[1] in ("ok", "error", "prefill"))
        shed = sum(1 for r in recs if r[1] == "shed")
        if admitted != m.get("total_requests") or \
                shed != m.get("total_shed"):
            bad.append(f"{name}: ledger {admitted} adm/{shed} shed vs "
                       f"counters {m.get('total_requests')}/"
                       f"{m.get('total_shed')}")
    checks.append(_check("replica-totals", not bad,
                         "; ".join(bad) if bad
                         else f"{len(rep_metrics)} live replicas agree"))

    # C5: controller aggregation == sum of live replica counters
    serve_metrics = server_view.get("serve_metrics") or {}
    if serve_metrics and rep_metrics:
        sum_req = sum(m.get("total_requests", 0)
                      for m in rep_metrics.values())
        sum_shed = sum(m.get("total_shed", 0)
                       for m in rep_metrics.values())
        agg_req = sum(d.get("requests_total", 0)
                      for d in serve_metrics.values())
        agg_shed = sum(d.get("shed_total", 0)
                       for d in serve_metrics.values())
        checks.append(_check(
            "serve-metrics-agree",
            agg_req == sum_req and agg_shed == sum_shed,
            f"controller {agg_req} req/{agg_shed} shed vs replicas "
            f"{sum_req}/{sum_shed}"))
    else:
        checks.append(_check("serve-metrics-agree", True,
                             "skipped (no serve metrics collected)"))

    # C6: the state engine's task table tells the same story
    delta = server_view.get("task_delta")
    if delta is not None:
        lossy = (delta.get("dropped", 0) > 0
                 or delta.get("events_dropped", 0) > 0)
        want_fin = len(ok_rids)
        want_fail = len(shed_rids) + len(failed_rids)
        got_fin = delta.get("finished", -1)
        got_fail = delta.get("failed", -1)
        if is_llm:
            # 1 logical request = 1 open + N polls on the task plane —
            # counts can't match 1:1; C10 carries exactness instead
            checks.append(_check(
                "state-engine-tasks", True,
                f"informational (LLM streaming protocol): FINISHED "
                f"{got_fin} task-plane calls for {want_fin} client-ok "
                f"logical requests"))
        elif lossy:
            checks.append(_check(
                "state-engine-tasks", True,
                f"skipped exact match: task table lossy "
                f"(dropped={delta.get('dropped')}, events_dropped="
                f"{delta.get('events_dropped')})"))
        elif tolerate:
            # SIGKILLed replicas both lose buffered events and can
            # leave an extra FINISHED behind a lost reply that the
            # client retried — exactness is only meaningful for
            # graceful scenarios, so report, don't grade
            checks.append(_check(
                "state-engine-tasks", True,
                f"informational (lost-record tolerance): FINISHED "
                f"{got_fin} vs client-ok {want_fin}, FAILED {got_fail} "
                f"vs client shed+failed {want_fail}"))
        else:
            checks.append(_check(
                "state-engine-tasks",
                got_fin == want_fin and got_fail == want_fail,
                f"FINISHED {got_fin} vs client-ok {want_fin}; FAILED "
                f"{got_fail} vs client shed+failed {want_fail}"))
    else:
        checks.append(_check("state-engine-tasks", True,
                             "skipped (no task delta collected)"))

    # C7: Prometheus exposition == the controller metrics it exports
    prom = (server_view.get("prometheus") or {}).get("serve")
    if prom is not None and serve_metrics:
        bad = []
        for dep, m in serve_metrics.items():
            g = prom.get(dep) or {}
            for prom_key, serve_key in (("requests_total",
                                         "requests_total"),
                                        ("shed_total", "shed_total")):
                if g.get(prom_key) is None or \
                        int(g[prom_key]) != int(m.get(serve_key, -1)):
                    bad.append(f"{dep}.{prom_key}: scraped "
                               f"{g.get(prom_key)} vs controller "
                               f"{m.get(serve_key)}")
        checks.append(_check("prometheus-serve-gauges", not bad,
                             "; ".join(bad) if bad
                             else f"{len(serve_metrics)} deployments "
                                  f"agree with /metrics"))
    else:
        checks.append(_check("prometheus-serve-gauges", True,
                             "skipped (no /metrics scrape)"))

    # C8: the faults that fired are the scenario's seeded schedule
    expected = server_view.get("chaos_expected")
    fired = server_view.get("chaos_fired")
    if expected:
        want = sorted((e["site"], e["op"], int(e.get("at", 1)))
                      for e in expected.get("schedule") or [])
        got = sorted((r.get("site"), r.get("op"), int(r.get("n", -1)))
                     for r in fired or [])
        checks.append(_check(
            "chaos-schedule-replay", want == got,
            f"expected {want} fired {got}"))
    elif fired:
        checks.append(_check("chaos-schedule-replay", False,
                             f"faults fired with no schedule: {fired}"))
    else:
        checks.append(_check("chaos-schedule-replay", True,
                             "no faults scheduled, none fired"))

    # C9: the tracing plane covers exactly what it sampled — every
    # head-sampled admitted request resolves to a complete span tree
    sampled_rids = server_view.get("traces_sampled")
    if sampled_rids is not None:
        from ray_tpu._private import tracing as _tracing
        traces = server_view.get("traces") or {}
        missing_tr, broken = [], []
        for rid in sampled_rids:
            spans = traces.get(rid)
            if not spans:
                missing_tr.append(rid)
                continue
            ok2, detail = _tracing.tree_complete(spans)
            if not ok2:
                broken.append(f"{rid}: {detail}")
        if server_view.get("traces_lossy"):
            checks.append(_check(
                "trace-complete", True,
                f"skipped exact match: trace table lossy "
                f"({len(missing_tr)} missing, {len(broken)} broken of "
                f"{len(sampled_rids)} sampled)"))
        elif tolerate and (missing_tr or broken):
            checks.append(_check(
                "trace-complete", True,
                f"{len(missing_tr)} traces lost with SIGKILLed "
                f"processes, {len(broken)} broken (tolerated)"))
        else:
            checks.append(_check(
                "trace-complete", not missing_tr and not broken,
                f"{len(sampled_rids)} sampled admitted requests, "
                f"{len(missing_tr)} without a trace"
                + (f" e.g. {missing_tr[:3]}" if missing_tr else "")
                + (f"; {len(broken)} incomplete trees, e.g. "
                   f"{broken[:2]}" if broken else "")))
    else:
        checks.append(_check("trace-complete", True,
                             "skipped (no traces collected)"))

    # C10: per-token join (LLM workload) — tokens the client RECEIVED
    # per request id == tokens the engine's ledger says it GENERATED
    client_tokens = server_view.get("llm_client_tokens")
    if is_llm and client_tokens is not None:
        server_tok: Dict[str, List[int]] = {}
        for led in server_view.get("llm_ledgers") or []:
            for rec in led.get("records") or []:
                rid, n = rec[0], int(rec[1])
                if rid is not None:
                    server_tok.setdefault(rid, []).append(n)
        missing_led, mismatched = [], []
        for rid in sorted(ok_rids):
            want = client_tokens.get(rid)
            got = server_tok.get(rid)
            if want is None or got is None:
                missing_led.append(rid)
            elif want not in got:
                mismatched.append(f"{rid}: client {want} vs engine "
                                  f"{got}")
        total_client = sum(client_tokens.get(r, 0) for r in ok_rids)
        if tolerate and (missing_led or mismatched):
            checks.append(_check(
                "llm-tokens", True,
                f"{len(missing_led)} ledgers lost with SIGKILLed "
                f"replicas, {len(mismatched)} mismatched (tolerated)"))
        else:
            checks.append(_check(
                "llm-tokens", not missing_led and not mismatched,
                f"{len(ok_rids)} streams, {total_client} tokens "
                f"client-side; {len(missing_led)} without an engine "
                f"ledger entry"
                + (f" e.g. {missing_led[:3]}" if missing_led else "")
                + (f"; {len(mismatched)} token-count mismatches, e.g. "
                   f"{mismatched[:2]}" if mismatched else "")))
    elif is_llm:
        checks.append(_check("llm-tokens", True,
                             "skipped (no client token counts)"))

    # C11: prefix-cache accounting (LLM workload) — two exact joins.
    # (a) Per live replica, the engine's cache_hit_tokens_total counter
    #     equals the sum of the cached-token column over that replica's
    #     token ledger (counter and ledger are written by the same
    #     commit path; drift means lost or double-counted hits).
    # (b) Per completed request, the engine ledger's recorded prompt
    #     length equals what the client actually sent — the cache-skip
    #     path must not silently shorten (or lengthen) a prompt.
    client_prompts = server_view.get("llm_client_prompts")
    if is_llm and client_prompts is not None and client_tokens is not None:
        llm_metrics = server_view.get("llm_metrics") or {}
        ledgers_by_replica: Dict[str, List[Any]] = {}
        for led in server_view.get("llm_ledgers") or []:
            ledgers_by_replica.setdefault(
                str(led.get("replica")), []).extend(
                    led.get("records") or [])
        drifted = []
        for hex_id, m in llm_metrics.items():
            counter = m.get("cache_hit_tokens_total")
            if counter is None:
                continue
            ledger_sum = sum(
                int(rec[4]) for rec in ledgers_by_replica.get(hex_id, [])
                if len(rec) > 4)
            if int(counter) != ledger_sum:
                drifted.append(f"{hex_id[:8]}: counter {counter} vs "
                               f"ledger {ledger_sum}")
        rows_by_rid: Dict[str, List[Any]] = {}
        for recs in ledgers_by_replica.values():
            for rec in recs:
                if rec[0] is not None:
                    rows_by_rid.setdefault(rec[0], []).append(rec)
        bad_prompt = []
        for rid in sorted(ok_rids):
            plen = client_prompts.get(rid)
            want = client_tokens.get(rid)
            if plen is None or want is None:
                continue  # C10 owns missing-ledger accounting
            rows = [rec for rec in rows_by_rid.get(rid, ())
                    if len(rec) > 3 and int(rec[1]) == int(want)]
            if rows and not any(int(rec[3]) == int(plen)
                                for rec in rows):
                bad_prompt.append(
                    f"{rid}: client prompt {plen} vs engine "
                    f"{sorted(int(r[3]) for r in rows)}")
        hit_total = sum(
            int(m.get("cache_hit_tokens_total") or 0)
            for m in llm_metrics.values())
        if tolerate and (drifted or bad_prompt):
            checks.append(_check(
                "llm-cache-hit", True,
                f"{len(drifted)} counter drifts, {len(bad_prompt)} "
                f"prompt mismatches with SIGKILLed replicas "
                f"(tolerated)"))
        else:
            checks.append(_check(
                "llm-cache-hit", not drifted and not bad_prompt,
                f"{hit_total} cache-hit tokens across "
                f"{len(llm_metrics)} live engines; "
                f"{len(drifted)} counter/ledger drifts"
                + (f" e.g. {drifted[:2]}" if drifted else "")
                + (f"; {len(bad_prompt)} prompt-length mismatches, "
                   f"e.g. {bad_prompt[:2]}" if bad_prompt else "")))
    elif is_llm:
        checks.append(_check("llm-cache-hit", True,
                             "skipped (no client prompt lengths)"))

    return {
        "ok": all(c["ok"] for c in checks),
        "checks": checks,
        "counts": {
            "client_ok": len(ok_rids),
            "client_shed": len(shed_rids),
            "client_unplaced": len(unplaced_rids),
            "client_failed": len(failed_rids),
            "server_completed": len(server_ok),
            "server_shed_listed": len(server_shed),
        },
    }
