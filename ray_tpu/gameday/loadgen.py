"""Deterministic open-loop load generation.

Two halves, split on purpose:

* ``build_schedule`` turns a phase spec + seed into a concrete list of
  ``Arrival``s ahead of time — a pure function, so the same (spec,
  seed) always produces byte-identical arrivals (request ids included)
  and a scenario can be replayed or diffed without running anything.

* ``OpenLoopRunner`` fires those arrivals at their *scheduled* instants
  regardless of how the system under test is doing, and measures each
  request's latency from its scheduled arrival — not from the moment a
  worker got around to sending it. That is the open-loop discipline:
  a closed-loop client that stalls behind a slow server silently stops
  generating load and reports healthy latencies for the requests it
  didn't send (coordinated omission). Here a stall shows up exactly
  where a real user would feel it — as queueing delay on every arrival
  scheduled during the stall.

Arrival processes are nonhomogeneous Poisson, sampled by Lewis-Shedler
thinning against each phase's peak rate, with heavy-tail (bounded
Pareto) request sizes and Zipf-skewed tenant assignment — all drawn
from one ``random.Random(seed)`` stream.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence


class Arrival:
    """One scheduled request: fires at ``t`` seconds after load start."""

    __slots__ = ("t", "rid", "phase", "tenant", "size")

    def __init__(self, t: float, rid: str, phase: str, tenant: str,
                 size: float):
        self.t = t
        self.rid = rid
        self.phase = phase
        self.tenant = tenant
        self.size = size  # heavy-tail work multiplier (1.0 = median-ish)

    def to_dict(self) -> Dict[str, Any]:
        return {"t": round(self.t, 6), "rid": self.rid,
                "phase": self.phase, "tenant": self.tenant,
                "size": round(self.size, 4)}


class ArrivalSchedule:
    def __init__(self, arrivals: List[Arrival],
                 phases: List[Dict[str, Any]], seed: int):
        self.arrivals = arrivals
        self.phases = phases
        self.seed = seed

    def __len__(self):
        return len(self.arrivals)

    @property
    def duration_s(self) -> float:
        return sum(float(p.get("duration_s", 0.0)) for p in self.phases)

    def rate_in(self, t0: float, t1: float) -> float:
        n = sum(1 for a in self.arrivals if t0 <= a.t < t1)
        return n / max(t1 - t0, 1e-9)


def _phase_rate(phase: Dict[str, Any], frac: float) -> float:
    """Instantaneous request rate at fraction ``frac`` of the phase.

    Shapes:
      steady       rps
      ramp         linear start_rps -> end_rps
      diurnal      half sine trough min_rps -> crest peak_rps -> trough
      flash_crowd  base_rps, with burst_rps inside the window
                   [burst_start_frac, burst_start_frac + burst_frac)
    """
    shape = phase.get("shape", "steady")
    if shape == "steady":
        return float(phase.get("rps", 10.0))
    if shape == "ramp":
        a = float(phase.get("start_rps", 10.0))
        b = float(phase.get("end_rps", 10.0))
        return a + (b - a) * frac
    if shape == "diurnal":
        lo = float(phase.get("min_rps", 10.0))
        hi = float(phase.get("peak_rps", 10.0))
        return lo + (hi - lo) * math.sin(math.pi * frac)
    if shape == "flash_crowd":
        base = float(phase.get("base_rps", 10.0))
        burst = float(phase.get("burst_rps", base))
        start = float(phase.get("burst_start_frac", 0.25))
        width = float(phase.get("burst_frac", 0.5))
        return burst if start <= frac < start + width else base
    raise ValueError(f"unknown load shape {shape!r}")


def _phase_peak(phase: Dict[str, Any]) -> float:
    shape = phase.get("shape", "steady")
    if shape == "steady":
        return float(phase.get("rps", 10.0))
    if shape == "ramp":
        return max(float(phase.get("start_rps", 10.0)),
                   float(phase.get("end_rps", 10.0)))
    if shape == "diurnal":
        return float(phase.get("peak_rps", 10.0))
    if shape == "flash_crowd":
        return max(float(phase.get("base_rps", 10.0)),
                   float(phase.get("burst_rps", 10.0)))
    raise ValueError(f"unknown load shape {shape!r}")


def _tenant_weights(n: int, skew: float) -> List[float]:
    # Zipf-ish: tenant i carries weight 1/(i+1)^skew; skew 0 = uniform
    return [1.0 / (i + 1) ** skew for i in range(n)]


def build_schedule(phases: Sequence[Dict[str, Any]], seed: int,
                   *, name: str = "gameday",
                   tenants: int = 4, tenant_skew: float = 1.2,
                   size_alpha: float = 1.8,
                   size_cap: float = 20.0) -> ArrivalSchedule:
    """Pure (spec, seed) -> arrivals. Request ids are sequential and
    embed the seed, so a replayed schedule is id-for-id identical and
    two different seeds can never alias in a shared ledger."""
    rng = random.Random(f"gameday:{seed}:{name}")
    weights = _tenant_weights(max(1, tenants), tenant_skew)
    tenant_names = [f"tenant-{i}" for i in range(max(1, tenants))]
    arrivals: List[Arrival] = []
    t_base = 0.0
    i = 0
    for phase in phases:
        dur = float(phase.get("duration_s", 0.0))
        if dur <= 0:
            continue
        peak = max(_phase_peak(phase), 1e-9)
        t = 0.0
        while True:
            # Lewis-Shedler thinning: candidate gaps at the peak rate,
            # accepted with probability rate(t)/peak — exact for a
            # nonhomogeneous Poisson process, and the draw count per
            # phase is a function of the seed alone
            t += rng.expovariate(peak)
            if t >= dur:
                break
            if rng.random() * peak > _phase_rate(phase, t / dur):
                continue
            # bounded Pareto sizes: median ~1, tail up to size_cap
            size = min(rng.paretovariate(size_alpha), size_cap)
            tenant = rng.choices(tenant_names, weights=weights)[0]
            arrivals.append(Arrival(
                t_base + t, f"{name}-{seed}-{i:06d}",
                phase.get("name", "phase"), tenant, size))
            i += 1
        t_base += dur
    return ArrivalSchedule(arrivals, list(phases), seed)


class RequestRecord:
    """Client-side truth for one request. ``latency_s`` runs from the
    SCHEDULED arrival to completion (open-loop; includes any dispatch
    or queueing delay); ``service_s`` from actual send to completion
    (diagnostic only)."""

    __slots__ = ("rid", "phase", "tenant", "size", "sched_t", "start_t",
                 "end_t", "outcome", "error")

    def __init__(self, arrival: Arrival, sched_t: float, start_t: float,
                 end_t: float, outcome: str, error: Optional[str]):
        self.rid = arrival.rid
        self.phase = arrival.phase
        self.tenant = arrival.tenant
        self.size = arrival.size
        self.sched_t = sched_t
        self.start_t = start_t
        self.end_t = end_t
        self.outcome = outcome  # "ok" | "shed" | "failed"
        self.error = error

    @property
    def latency_s(self) -> float:
        return max(0.0, self.end_t - self.sched_t)

    @property
    def service_s(self) -> float:
        return max(0.0, self.end_t - self.start_t)


class OpenLoopRunner:
    """Fires a precomputed schedule open-loop.

    ``send`` is called with each ``Arrival`` and either returns (ok) or
    raises; ``classify`` maps the exception to ``"shed"`` or
    ``"failed"``. A bounded worker pool executes sends; if every worker
    is busy when an arrival is due it is dispatched late and the
    lateness is charged to that request's latency — never silently
    skipped (that would be coordinated omission by another name).
    """

    def __init__(self, schedule: ArrivalSchedule,
                 send: Callable[[Arrival], Any],
                 classify: Optional[Callable[[BaseException], str]] = None,
                 max_workers: int = 32):
        self._schedule = schedule
        self._send = send
        self._classify = classify or (lambda e: "failed")
        self._max_workers = max(1, int(max_workers))
        self._lock = threading.Lock()
        self.records: List[RequestRecord] = []
        self.started_at: Optional[float] = None

    def _fire(self, arrival: Arrival, sched_abs: float):
        start = time.time()
        outcome, err = "ok", None
        try:
            self._send(arrival)
        except BaseException as e:  # noqa: BLE001 — every failure counts
            outcome = self._classify(e)
            if outcome not in ("shed", "failed"):
                outcome = "failed"
            err = f"{type(e).__name__}: {e}".split("\n")[0][:200]
        rec = RequestRecord(arrival, sched_abs, start, time.time(),
                            outcome, err)
        with self._lock:
            self.records.append(rec)

    def run(self, on_phase: Optional[Callable[[str], None]] = None
            ) -> List[RequestRecord]:
        """Blocks until every scheduled request has completed."""
        from concurrent.futures import ThreadPoolExecutor

        t0 = time.time()
        self.started_at = t0
        cur_phase = None
        with ThreadPoolExecutor(max_workers=self._max_workers,
                                thread_name_prefix="gameday") as pool:
            for arrival in self._schedule.arrivals:
                sched_abs = t0 + arrival.t
                delay = sched_abs - time.time()
                if delay > 0:
                    time.sleep(delay)
                if on_phase is not None and arrival.phase != cur_phase:
                    cur_phase = arrival.phase
                    on_phase(cur_phase)
                pool.submit(self._fire, arrival, sched_abs)
        with self._lock:
            return list(self.records)
