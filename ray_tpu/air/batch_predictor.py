"""BatchPredictor — offline inference over a Dataset.

Reference analogue: air BatchPredictor + predictor base. A checkpoint's
model runs over dataset batches via map_batches actors; JAX predictors
jit once per (bucketed) batch shape.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """Base single-process predictor."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs
                        ) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]
                ) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a flax apply function + params from a checkpoint."""

    def __init__(self, params: Any,
                 apply_fn: Callable[[Any, np.ndarray], Any],
                 input_column: str = "x",
                 output_column: str = "predictions"):
        import jax
        self.params = params
        self._jitted = jax.jit(apply_fn)
        self.input_column = input_column
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable,
                        input_column: str = "x",
                        output_column: str = "predictions"
                        ) -> "JaxPredictor":
        state = checkpoint.to_dict()
        params = state.get("params") or state.get("state", {}).get(
            "params") or state
        return cls(params, apply_fn, input_column, output_column)

    def predict(self, batch):
        import jax.numpy as jnp
        x = jnp.asarray(batch[self.input_column])
        out = np.asarray(self._jitted(self.params, x))
        res = dict(batch)
        res[self.output_column] = out
        return res


class BatchPredictor:
    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **predictor_kwargs):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(self, dataset, *, batch_size: int = 256,
                num_workers: Optional[int] = None,
                apply_preprocessor: bool = True):
        """Run inference over every batch of the dataset; returns a new
        Dataset with the prediction column appended.

        ``num_workers`` shards the work over a Dataset actor pool (each
        actor holds ONE predictor instance — the model loads once per
        worker, not once per block).  A preprocessor attached to the
        checkpoint (``Checkpoint.with_preprocessor``) is applied to each
        batch first, so inference sees the training-time features
        (reference: BatchPredictor.predict + separate_gpu_stage)."""
        checkpoint = self.checkpoint
        predictor_cls = self.predictor_cls
        kwargs = self.predictor_kwargs
        preprocessor = checkpoint.get_preprocessor() \
            if apply_preprocessor else None
        state = {"p": None}

        def _predict(batch):
            if state["p"] is None:  # one predictor per worker/actor
                state["p"] = predictor_cls.from_checkpoint(
                    checkpoint, **kwargs)
            if preprocessor is not None:
                batch = preprocessor.transform_batch(batch)
            return state["p"].predict(batch)

        compute = None
        if num_workers is not None:
            from ray_tpu.data._internal.compute import ActorPoolStrategy
            compute = ActorPoolStrategy(size=num_workers)
        return dataset.map_batches(_predict, batch_size=batch_size,
                                   compute=compute)
