"""Preprocessors — fit/transform over Datasets.

Reference analogue: ray.air preprocessor base + ray.data.preprocessors
(StandardScaler, MinMaxScaler, LabelEncoder, Chain, BatchMapper).
Fitting aggregates statistics across dataset blocks; transform maps
batches, so it parallelizes over the block tasks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, dataset) -> "Preprocessor":
        self._fit(dataset)
        self._fitted = True
        return self

    def transform(self, dataset):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return dataset.map_batches(self._transform_batch)

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return self._transform_batch(batch)

    def _needs_fit(self) -> bool:
        return True

    def _fit(self, dataset):
        raise NotImplementedError

    def _transform_batch(self, batch):
        raise NotImplementedError


def _iter_column(dataset, col: str):
    for batch in dataset.iter_batches():
        if col in batch:
            yield np.asarray(batch[col], np.float64)


def _fit_categories(dataset, columns: List[str]) -> Dict[str, List[Any]]:
    """One pass collecting the distinct values of several columns —
    shared by the categorical encoders.  Mixed-type columns sort by
    (typename, repr) so fitting never raises on e.g. {'x', 1.0}."""
    seen: Dict[str, set] = {c: set() for c in columns}
    for batch in dataset.iter_batches():
        for col in columns:
            if col in batch:
                seen[col].update(np.asarray(batch[col]).tolist())
    return {c: sorted(v, key=lambda x: (type(x).__name__, repr(x)))
            for c, v in seen.items()}


class StandardScaler(Preprocessor):
    """z-score scaling per column."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Any] = {}

    def _fit(self, dataset):
        for col in self.columns:
            n, s, s2 = 0, 0.0, 0.0
            for arr in _iter_column(dataset, col):
                n += arr.size
                s += float(arr.sum())
                s2 += float((arr ** 2).sum())
            mean = s / max(n, 1)
            var = max(s2 / max(n, 1) - mean ** 2, 0.0)
            self.stats_[col] = (mean, float(np.sqrt(var)) or 1.0)

    def _transform_batch(self, batch):
        out = dict(batch)
        for col, (mean, std) in self.stats_.items():
            if col in out:
                out[col] = ((np.asarray(out[col], np.float64) - mean)
                            / (std if std > 0 else 1.0)).astype(
                    np.float32)
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Any] = {}

    def _fit(self, dataset):
        for col in self.columns:
            lo, hi = np.inf, -np.inf
            for arr in _iter_column(dataset, col):
                lo = min(lo, float(arr.min()))
                hi = max(hi, float(arr.max()))
            self.stats_[col] = (lo, hi)

    def _transform_batch(self, batch):
        out = dict(batch)
        for col, (lo, hi) in self.stats_.items():
            if col in out:
                rng = (hi - lo) or 1.0
                out[col] = ((np.asarray(out[col], np.float64) - lo)
                            / rng).astype(np.float32)
        return out


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: List[Any] = []

    def _fit(self, dataset):
        self.classes_ = _fit_categories(
            dataset, [self.label_column])[self.label_column]

    def _transform_batch(self, batch):
        out = dict(batch)
        if self.label_column in out:
            idx = {c: i for i, c in enumerate(self.classes_)}
            out[self.label_column] = np.asarray(
                [idx[v] for v in np.asarray(
                    out[self.label_column]).tolist()], np.int64)
        return out


class OrdinalEncoder(Preprocessor):
    """Category -> index for several columns (reference:
    data.preprocessors.OrdinalEncoder)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.classes_: Dict[str, List[Any]] = {}

    def _fit(self, dataset):
        self.classes_ = _fit_categories(dataset, self.columns)

    def _transform_batch(self, batch):
        out = dict(batch)
        for col, classes in self.classes_.items():
            if col in out:
                idx = {c: i for i, c in enumerate(classes)}
                out[col] = np.asarray(
                    [idx[v] for v in np.asarray(out[col]).tolist()],
                    np.int64)
        return out


class OneHotEncoder(Preprocessor):
    """Category -> one-hot vector column ``<col>_onehot`` (reference:
    data.preprocessors.OneHotEncoder)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.classes_: Dict[str, List[Any]] = {}

    def _fit(self, dataset):
        self.classes_ = _fit_categories(dataset, self.columns)

    def _transform_batch(self, batch):
        out = dict(batch)
        for col, classes in self.classes_.items():
            if col in out:
                idx = {c: i for i, c in enumerate(classes)}
                vals = np.asarray(out.pop(col)).tolist()
                oh = np.zeros((len(vals), len(classes)), np.float32)
                for r, v in enumerate(vals):
                    if v in idx:
                        oh[r, idx[v]] = 1.0
                out[f"{col}_onehot"] = oh
        return out


class SimpleImputer(Preprocessor):
    """Fill NaNs with a per-column statistic (reference:
    data.preprocessors.SimpleImputer; strategies mean | most_frequent |
    constant)."""

    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Optional[float] = None):
        if strategy not in ("mean", "most_frequent", "constant"):
            raise ValueError(f"unknown imputer strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' requires fill_value")
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, Any] = {}

    def _needs_fit(self) -> bool:
        return self.strategy != "constant"

    def _fit(self, dataset):
        if self.strategy == "constant":
            return
        for col in self.columns:
            if self.strategy == "mean":
                n, s = 0, 0.0
                for arr in _iter_column(dataset, col):
                    good = arr[~np.isnan(arr)]
                    n += good.size
                    s += float(good.sum())
                self.stats_[col] = s / max(n, 1)
            else:  # most_frequent
                from collections import Counter
                counts: Counter = Counter()
                for batch in dataset.iter_batches():
                    if col in batch:
                        vals = np.asarray(batch[col])
                        if vals.dtype.kind == "f":
                            vals = vals[~np.isnan(vals)]
                        counts.update(
                            v for v in vals.tolist()
                            if v is not None and not (
                                isinstance(v, float) and np.isnan(v)))
                self.stats_[col] = counts.most_common(1)[0][0] \
                    if counts else 0.0

    def _transform_batch(self, batch):
        out = dict(batch)
        for col in self.columns:
            if col not in out:
                continue
            fill = self.fill_value if self.strategy == "constant" \
                else self.stats_.get(col, 0.0)
            arr = np.asarray(out[col])
            if arr.dtype.kind == "f":
                arr = np.where(np.isnan(arr), float(fill), arr)
                out[col] = arr.astype(np.float32)
            else:
                # categorical (string/object) columns: impute the
                # missing sentinels, keep the dtype
                vals = arr.tolist()
                out[col] = np.asarray(
                    [fill if v is None
                     or (isinstance(v, float) and np.isnan(v)) else v
                     for v in vals])
        return out


class RobustScaler(Preprocessor):
    """(x - median) / IQR scaling — outlier-insensitive (reference:
    data.preprocessors.RobustScaler).  Quantiles are computed on the
    concatenated column (datasets here are block-iterable in one
    process; the reference approximates the same way via aggregate)."""

    def __init__(self, columns: List[str],
                 quantile_range=(0.25, 0.75)):
        self.columns = columns
        self.quantile_range = quantile_range
        self.stats_: Dict[str, Any] = {}

    def _fit(self, dataset):
        lo_q, hi_q = self.quantile_range
        for col in self.columns:
            chunks = list(_iter_column(dataset, col))
            if not chunks:
                continue
            arr = np.concatenate([c.ravel() for c in chunks])
            med = float(np.median(arr))
            iqr = float(np.quantile(arr, hi_q) - np.quantile(arr, lo_q))
            self.stats_[col] = (med, iqr or 1.0)

    def _transform_batch(self, batch):
        out = dict(batch)
        for col, (med, iqr) in self.stats_.items():
            if col in out:
                out[col] = ((np.asarray(out[col], np.float64) - med)
                            / iqr).astype(np.float32)
        return out


class MaxAbsScaler(Preprocessor):
    """x / max|x| per column (reference: data.preprocessors.MaxAbsScaler)."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, float] = {}

    def _fit(self, dataset):
        for col in self.columns:
            m = 0.0
            for arr in _iter_column(dataset, col):
                m = max(m, float(np.abs(arr).max()))
            self.stats_[col] = m or 1.0

    def _transform_batch(self, batch):
        out = dict(batch)
        for col, m in self.stats_.items():
            if col in out:
                out[col] = (np.asarray(out[col], np.float64) / m).astype(
                    np.float32)
        return out


class Normalizer(Preprocessor):
    """Row-wise unit-norm scaling across a set of columns — stateless
    (reference: data.preprocessors.Normalizer)."""

    def __init__(self, columns: List[str], norm: str = "l2"):
        if norm not in ("l1", "l2", "max"):
            raise ValueError(f"unknown norm {norm!r}")
        self.columns = columns
        self.norm = norm

    def _needs_fit(self) -> bool:
        return False

    def _fit(self, dataset):
        pass

    def _transform_batch(self, batch):
        out = dict(batch)
        cols = [np.asarray(out[c], np.float64) for c in self.columns
                if c in out]
        if not cols:
            return out
        mat = np.stack(cols, axis=-1)
        if self.norm == "l2":
            d = np.sqrt((mat ** 2).sum(-1))
        elif self.norm == "l1":
            d = np.abs(mat).sum(-1)
        else:
            d = np.abs(mat).max(-1)
        d = np.where(d == 0, 1.0, d)
        for i, c in enumerate([c for c in self.columns if c in out]):
            out[c] = (mat[..., i] / d).astype(np.float32)
        return out


class Concatenator(Preprocessor):
    """Pack feature columns into one 2-D matrix column — the shape
    models consume (reference: data.preprocessors.Concatenator)."""

    def __init__(self, columns: List[str], output_column: str = "x",
                 dtype=np.float32):
        self.columns = columns
        self.output_column = output_column
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _fit(self, dataset):
        pass

    def _transform_batch(self, batch):
        out = dict(batch)
        parts = []
        for c in self.columns:
            if c in out:
                a = np.asarray(out.pop(c))
                parts.append(a if a.ndim > 1 else a[:, None])
        if parts:
            out[self.output_column] = np.concatenate(
                parts, axis=1).astype(self.dtype)
        return out


class BatchMapper(Preprocessor):
    """Stateless user-function preprocessor."""

    def __init__(self, fn: Callable[[Dict[str, np.ndarray]],
                                    Dict[str, np.ndarray]]):
        self.fn = fn

    def _needs_fit(self) -> bool:
        return False

    def _fit(self, dataset):
        pass

    def _transform_batch(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def _fit(self, dataset):
        for p in self.preprocessors:
            dataset = p.fit_transform(dataset)

    def _transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch

    def transform(self, dataset):
        for p in self.preprocessors:
            dataset = p.transform(dataset)
        return dataset
