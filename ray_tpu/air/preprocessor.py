"""Preprocessors — fit/transform over Datasets.

Reference analogue: ray.air preprocessor base + ray.data.preprocessors
(StandardScaler, MinMaxScaler, LabelEncoder, Chain, BatchMapper).
Fitting aggregates statistics across dataset blocks; transform maps
batches, so it parallelizes over the block tasks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, dataset) -> "Preprocessor":
        self._fit(dataset)
        self._fitted = True
        return self

    def transform(self, dataset):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return dataset.map_batches(self._transform_batch)

    def fit_transform(self, dataset):
        return self.fit(dataset).transform(dataset)

    def transform_batch(self, batch: Dict[str, np.ndarray]
                        ) -> Dict[str, np.ndarray]:
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return self._transform_batch(batch)

    def _needs_fit(self) -> bool:
        return True

    def _fit(self, dataset):
        raise NotImplementedError

    def _transform_batch(self, batch):
        raise NotImplementedError


def _iter_column(dataset, col: str):
    for batch in dataset.iter_batches():
        if col in batch:
            yield np.asarray(batch[col], np.float64)


class StandardScaler(Preprocessor):
    """z-score scaling per column."""

    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Any] = {}

    def _fit(self, dataset):
        for col in self.columns:
            n, s, s2 = 0, 0.0, 0.0
            for arr in _iter_column(dataset, col):
                n += arr.size
                s += float(arr.sum())
                s2 += float((arr ** 2).sum())
            mean = s / max(n, 1)
            var = max(s2 / max(n, 1) - mean ** 2, 0.0)
            self.stats_[col] = (mean, float(np.sqrt(var)) or 1.0)

    def _transform_batch(self, batch):
        out = dict(batch)
        for col, (mean, std) in self.stats_.items():
            if col in out:
                out[col] = ((np.asarray(out[col], np.float64) - mean)
                            / (std if std > 0 else 1.0)).astype(
                    np.float32)
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: Dict[str, Any] = {}

    def _fit(self, dataset):
        for col in self.columns:
            lo, hi = np.inf, -np.inf
            for arr in _iter_column(dataset, col):
                lo = min(lo, float(arr.min()))
                hi = max(hi, float(arr.max()))
            self.stats_[col] = (lo, hi)

    def _transform_batch(self, batch):
        out = dict(batch)
        for col, (lo, hi) in self.stats_.items():
            if col in out:
                rng = (hi - lo) or 1.0
                out[col] = ((np.asarray(out[col], np.float64) - lo)
                            / rng).astype(np.float32)
        return out


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: List[Any] = []

    def _fit(self, dataset):
        seen = set()
        for batch in dataset.iter_batches():
            if self.label_column in batch:
                seen.update(np.asarray(
                    batch[self.label_column]).tolist())
        self.classes_ = sorted(seen)

    def _transform_batch(self, batch):
        out = dict(batch)
        if self.label_column in out:
            idx = {c: i for i, c in enumerate(self.classes_)}
            out[self.label_column] = np.asarray(
                [idx[v] for v in np.asarray(
                    out[self.label_column]).tolist()], np.int64)
        return out


class BatchMapper(Preprocessor):
    """Stateless user-function preprocessor."""

    def __init__(self, fn: Callable[[Dict[str, np.ndarray]],
                                    Dict[str, np.ndarray]]):
        self.fn = fn

    def _needs_fit(self) -> bool:
        return False

    def _fit(self, dataset):
        pass

    def _transform_batch(self, batch):
        return self.fn(batch)


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def _fit(self, dataset):
        for p in self.preprocessors:
            dataset = p.fit_transform(dataset)

    def _transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch

    def transform(self, dataset):
        for p in self.preprocessors:
            dataset = p.transform(dataset)
        return dataset
