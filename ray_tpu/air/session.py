"""Train/Tune session: the worker-side reporting API.

Reference analogue: python/ray/air/session.py — report:41, get_checkpoint:94,
get_dataset_shard:345, world_rank/local_rank accessors. A session is
installed thread-locally in each train worker (and in function trainables);
``report`` enqueues a TrainingResult consumed by the BackendExecutor/Tune.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class TrainingResult:
    metrics: Dict[str, Any]
    checkpoint: Optional[Any] = None


@dataclass
class _Session:
    world_rank: int = 0
    local_rank: int = 0
    node_rank: int = 0
    world_size: int = 1
    trial_name: str = ""
    trial_id: str = ""
    experiment_name: str = ""
    checkpoint: Optional[Any] = None
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    result_queue: "queue.Queue[TrainingResult]" = field(
        default_factory=queue.Queue)
    stop_event: threading.Event = field(default_factory=threading.Event)
    tpu_chips: tuple = ()
    mesh: Any = None  # the SPMD island's jax Mesh, set by the backend
    # durable checkpoint engine (ray_tpu.checkpoint): set up by the
    # backend when the run has a checkpoint root; report(checkpoint=...)
    # then stages through the manager instead of shipping payloads in-band
    checkpoint_manager: Any = None
    ckpt_next_step: int = 0
    async_checkpointer: Any = None


_tls = threading.local()

# Preemption state is PROCESS-global, not session-local: the raylet's
# preemption_notice lands on the worker's io thread while the train_func
# runs on its own thread — a thread-local could never cross that gap.
_preempt_lock = threading.Lock()
_preempt_state: Dict[str, Any] = {"deadline_unix": None, "grace_s": None}
_preempt_event = threading.Event()


def mark_preempted(deadline_unix: Optional[float] = None,
                   grace_s: Optional[float] = None):
    """Record a preemption notice for this process (called by the worker
    runtime when the raylet starts draining)."""
    with _preempt_lock:
        _preempt_state["deadline_unix"] = deadline_unix
        _preempt_state["grace_s"] = grace_s
    _preempt_event.set()


def preempted() -> bool:
    """True once this process received a preemption notice. Train loops
    poll this each step and commit an out-of-band checkpoint (via
    ``get_async_checkpointer()`` + ``report``) inside the grace window."""
    return _preempt_event.is_set()


def preemption_deadline() -> Optional[float]:
    """Unix time the node dies (None when not preempted / not given)."""
    with _preempt_lock:
        return _preempt_state["deadline_unix"]


def _clear_preempted():
    """Test/restart hook: a fresh worker process starts unpreempted;
    this resets the flag for in-process reuse."""
    with _preempt_lock:
        _preempt_state["deadline_unix"] = None
        _preempt_state["grace_s"] = None
    _preempt_event.clear()


def _set_session(s: Optional[_Session]):
    _tls.session = s


def _get_session(warn: bool = True) -> Optional[_Session]:
    s = getattr(_tls, "session", None)
    return s


def in_session() -> bool:
    return _get_session() is not None


def report(metrics: Dict[str, Any], *, checkpoint=None):
    s = _get_session()
    if s is None:
        raise RuntimeError("session.report() called outside a train session")
    if checkpoint is not None and s.checkpoint_manager is not None:
        checkpoint = _route_through_manager(s, checkpoint)
    s.result_queue.put(TrainingResult(dict(metrics), checkpoint))
    if s.stop_event.is_set():
        raise StopIteration("session stopped")


def _route_through_manager(s: _Session, checkpoint):
    """Stage the payload under the durable checkpoint root and ship only a
    PendingCheckpoint marker; the driver commits after the round barrier
    (all ranks staged). Replicated dict/dir payloads are written by rank 0
    only; a PendingCheckpoint (from an AsyncCheckpointer the train_func
    drives itself) passes through untouched."""
    from ray_tpu.checkpoint import PendingCheckpoint
    if isinstance(checkpoint, PendingCheckpoint):
        s.ckpt_next_step = max(s.ckpt_next_step, checkpoint.step + 1)
        return checkpoint
    step = s.ckpt_next_step
    s.ckpt_next_step += 1
    if s.world_rank == 0:
        s.checkpoint_manager.stage(step, checkpoint)
    return PendingCheckpoint(step)


def get_checkpoint():
    s = _get_session()
    return s.checkpoint if s else None


def get_checkpoint_manager():
    """The run's durable CheckpointManager, or None when the run has no
    checkpoint root configured (RunConfig.name/storage_path)."""
    s = _get_session()
    return s.checkpoint_manager if s else None


def next_checkpoint_step() -> int:
    """The step number the next staged checkpoint will get (monotonic,
    continues across gang restarts)."""
    s = _get_session()
    return s.ckpt_next_step if s else 0


def get_async_checkpointer():
    """This worker's AsyncCheckpointer bound to the run's checkpoint root
    (lazily created). Train funcs use it for sharded SPMD state:
    ``pending = ckpter.save(session.next_checkpoint_step(), state)`` then
    ``session.report(metrics, checkpoint=pending)`` — the driver commits
    once every rank's write lands. Returns None without a manager."""
    s = _get_session()
    if s is None or s.checkpoint_manager is None:
        return None
    if s.async_checkpointer is None:
        from ray_tpu.checkpoint import AsyncCheckpointer
        s.async_checkpointer = AsyncCheckpointer(
            s.checkpoint_manager, process_index=s.world_rank,
            process_count=s.world_size, commit=False)
    return s.async_checkpointer


def get_dataset_shard(name: str = "train"):
    s = _get_session()
    if s is None:
        return None
    return s.dataset_shards.get(name)


def get_world_rank() -> int:
    s = _get_session()
    return s.world_rank if s else 0


def get_local_rank() -> int:
    s = _get_session()
    return s.local_rank if s else 0


def get_node_rank() -> int:
    s = _get_session()
    return s.node_rank if s else 0


def get_world_size() -> int:
    s = _get_session()
    return s.world_size if s else 1


def get_trial_name() -> str:
    s = _get_session()
    return s.trial_name if s else ""


def get_trial_id() -> str:
    s = _get_session()
    return s.trial_id if s else ""


def get_experiment_name() -> str:
    s = _get_session()
    return s.experiment_name if s else ""


def get_mesh():
    """The SPMD island's jax.sharding.Mesh (TPU-first addition: set up by the
    Jax backend so train_funcs never build meshes by hand)."""
    s = _get_session()
    return s.mesh if s else None


def get_tpu_chips() -> tuple:
    s = _get_session()
    return s.tpu_chips if s else ()
