"""AIR Checkpoint: one object, multiple representations.

Reference semantics: python/ray/air/checkpoint.py:60 — a Checkpoint is
interconvertible between dict ↔ local directory ↔ object ref (URI form is a
directory in shared storage). TPU-first addition: a *sharded* form — each
host of an SPMD island writes only its param shards (orbax-style,
one subdir per process) and restore reassembles on the same or a compatible
mesh (SURVEY.md §5.4 TPU equivalent).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None):
        if (data is None) == (directory is None):
            raise ValueError("provide exactly one of data dict / directory")
        self._data = data
        self._dir = directory

    # ------------------------------------------------------------- creators

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=path)

    @classmethod
    def from_object_ref(cls, ref) -> "Checkpoint":
        import ray_tpu
        return ray_tpu.get(ref)

    # ------------------------------------------------------------ converters

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        blob = os.path.join(self._dir, "checkpoint.pkl")
        if os.path.exists(blob):
            with open(blob, "rb") as f:
                return pickle.load(f)
        out: Dict[str, Any] = {}
        for name in os.listdir(self._dir):
            with open(os.path.join(self._dir, name), "rb") as f:
                out[name] = f.read()
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = os.path.join(tempfile.gettempdir(), "rtpu_ckpt",
                                uuid.uuid4().hex)
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
        else:
            with open(os.path.join(path, "checkpoint.pkl"), "wb") as f:
                pickle.dump(self._data, f, protocol=5)
        return path

    def to_object_ref(self):
        import ray_tpu
        if self._dir is not None:
            # materialize as dict so the object is self-contained
            return ray_tpu.put(Checkpoint.from_dict(self.to_dict()))
        return ray_tpu.put(self)

    # ----------------------------------------------------------- state/value

    # -------------------------------------------------------- preprocessor

    _PREPROCESSOR_KEY = "_preprocessor"

    def with_preprocessor(self, preprocessor) -> "Checkpoint":
        """Attach a fitted preprocessor so inference applies the exact
        training-time transform (reference: air/checkpoint.py
        get_preprocessor — the preprocessor rides the checkpoint)."""
        import cloudpickle
        data = self.to_dict()
        data[self._PREPROCESSOR_KEY] = cloudpickle.dumps(preprocessor)
        return Checkpoint.from_dict(data)

    def get_preprocessor(self):
        import cloudpickle
        blob = self.to_dict().get(self._PREPROCESSOR_KEY)
        return cloudpickle.loads(blob) if blob is not None else None

    def get(self, key: str, default=None):
        return self.to_dict().get(key, default)

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._dir}"
        return f"Checkpoint({kind})"


class ShardedCheckpoint:
    """Multi-host sharded train-state checkpoint (TPU-first addition).

    save(): every process writes its addressable shards under
    ``root/process_<i>/``; restore() reassembles on a mesh with the same
    sharding. Uses orbax when available, tensorstore-free fallback writes
    raw numpy per shard.
    """

    def __init__(self, root: str):
        self.root = root

    def save(self, state, process_index: Optional[int] = None) -> str:
        import jax
        import numpy as np
        from jax.tree_util import tree_flatten_with_path

        idx = process_index if process_index is not None \
            else jax.process_index()
        pdir = os.path.join(self.root, f"process_{idx}")
        os.makedirs(pdir, exist_ok=True)
        leaves, _ = tree_flatten_with_path(state)
        manifest = []
        for path, leaf in leaves:
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                           for p in path)
            if hasattr(leaf, "addressable_shards"):
                for shard in leaf.addressable_shards:
                    fname = f"{abs(hash((key, str(shard.index))))}.npy"
                    np.save(os.path.join(pdir, fname),
                            np.asarray(shard.data))
                    manifest.append({"key": key, "file": fname,
                                     "index": _index_to_json(shard.index),
                                     "shape": list(leaf.shape),
                                     "dtype": str(leaf.dtype)})
            else:
                fname = f"{abs(hash((key, 'full')))}.npy"
                np.save(os.path.join(pdir, fname), np.asarray(leaf))
                manifest.append({"key": key, "file": fname, "index": None,
                                 "shape": list(np.shape(leaf)),
                                 "dtype": str(np.asarray(leaf).dtype)})
        import json
        with open(os.path.join(pdir, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        return self.root

    def restore(self, target_state):
        """Restore into arrays shaped/sharded like target_state."""
        import json
        import jax
        import numpy as np
        from jax.tree_util import tree_flatten_with_path, tree_unflatten

        entries: Dict[str, list] = {}
        for pname in sorted(os.listdir(self.root)):
            pdir = os.path.join(self.root, pname)
            mf = os.path.join(pdir, "manifest.json")
            if not os.path.exists(mf):
                continue
            with open(mf) as f:
                for e in json.load(f):
                    e["dir"] = pdir
                    entries.setdefault(e["key"], []).append(e)
        leaves, treedef = tree_flatten_with_path(target_state)
        out = []
        for path, leaf in leaves:
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                           for p in path)
            shards = entries.get(key)
            if not shards:
                raise KeyError(f"checkpoint missing {key}")
            full = np.zeros(shards[0]["shape"],
                            dtype=np.dtype(shards[0]["dtype"]))
            for e in shards:
                data = np.load(os.path.join(e["dir"], e["file"]))
                if e["index"] is None:
                    full = data
                else:
                    full[_json_to_index(e["index"])] = data
            if hasattr(leaf, "sharding"):
                out.append(jax.device_put(full, leaf.sharding))
            else:
                out.append(full)
        return tree_unflatten(treedef, out)


def _index_to_json(index):
    return [[s.start, s.stop, s.step] for s in index]


def _json_to_index(idx_json):
    return tuple(slice(a, b, c) for a, b, c in idx_json)
