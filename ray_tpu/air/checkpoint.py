"""AIR Checkpoint: one object, multiple representations.

Reference semantics: python/ray/air/checkpoint.py:60 — a Checkpoint is
interconvertible between dict ↔ local directory ↔ object ref (URI form is a
directory in shared storage). TPU-first addition: a *sharded* form — each
host of an SPMD island writes only its param shards (orbax-style,
one subdir per process) and restore reassembles on the same or a compatible
mesh (SURVEY.md §5.4 TPU equivalent).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None):
        if (data is None) == (directory is None):
            raise ValueError("provide exactly one of data dict / directory")
        self._data = data
        self._dir = directory

    # ------------------------------------------------------------- creators

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=path)

    @classmethod
    def from_object_ref(cls, ref) -> "Checkpoint":
        import ray_tpu
        return ray_tpu.get(ref)

    # ------------------------------------------------------------ converters

    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        blob = os.path.join(self._dir, "checkpoint.pkl")
        if os.path.exists(blob):
            with open(blob, "rb") as f:
                return pickle.load(f)
        # raw-file checkpoint: walk recursively so sharded layouts
        # (process_<i>/ subdirs) flatten to relative-path keys instead of
        # raising IsADirectoryError
        out: Dict[str, Any] = {}
        for dirpath, _dirnames, filenames in os.walk(self._dir):
            for name in filenames:
                fpath = os.path.join(dirpath, name)
                with open(fpath, "rb") as f:
                    out[os.path.relpath(fpath, self._dir)] = f.read()
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        """Materialize into ``path`` crash-safely: content is written to a
        staging dir next to the target and swapped in with an atomic
        rename, so a failure mid-write leaves either the old directory or
        nothing — never a half-materialized checkpoint."""
        if path is None:
            path = os.path.join(tempfile.gettempdir(), "rtpu_ckpt",
                                uuid.uuid4().hex)
        path = os.path.abspath(path)
        if self._dir is not None and os.path.abspath(self._dir) == path:
            return path
        parent = os.path.dirname(path)
        os.makedirs(parent, exist_ok=True)
        staging = tempfile.mkdtemp(
            prefix=f".{os.path.basename(path)}.part-", dir=parent)
        try:
            if self._dir is not None:
                shutil.copytree(self._dir, staging, dirs_exist_ok=True)
            else:
                with open(os.path.join(staging, "checkpoint.pkl"),
                          "wb") as f:
                    pickle.dump(self._data, f, protocol=5)
            if os.path.isdir(path):
                shutil.rmtree(path)
            os.rename(staging, path)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        return path

    def to_object_ref(self):
        import ray_tpu
        if self._dir is not None:
            # materialize as dict so the object is self-contained
            return ray_tpu.put(Checkpoint.from_dict(self.to_dict()))
        return ray_tpu.put(self)

    # ----------------------------------------------------------- state/value

    # -------------------------------------------------------- preprocessor

    _PREPROCESSOR_KEY = "_preprocessor"

    def with_preprocessor(self, preprocessor) -> "Checkpoint":
        """Attach a fitted preprocessor so inference applies the exact
        training-time transform (reference: air/checkpoint.py
        get_preprocessor — the preprocessor rides the checkpoint)."""
        import cloudpickle
        data = self.to_dict()
        data[self._PREPROCESSOR_KEY] = cloudpickle.dumps(preprocessor)
        return Checkpoint.from_dict(data)

    def get_preprocessor(self):
        import cloudpickle
        blob = self.to_dict().get(self._PREPROCESSOR_KEY)
        return cloudpickle.loads(blob) if blob is not None else None

    def get(self, key: str, default=None):
        return self.to_dict().get(key, default)

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._dir}"
        return f"Checkpoint({kind})"


class ShardedCheckpoint:
    """Multi-host sharded train-state checkpoint (TPU-first addition).

    save(): every process writes its addressable shards under
    ``root/process_<i>/``; restore() reassembles on a mesh with the same
    sharding. Uses orbax when available, tensorstore-free fallback writes
    raw numpy per shard.
    """

    def __init__(self, root: str):
        self.root = root

    def save(self, state, process_index: Optional[int] = None) -> str:
        """Write this process's owned shards. Filenames are deterministic
        sanitized ``key__shard<i>.npy`` (str hash() is salted per process
        — the old ``abs(hash((key, index)))`` names differed across hosts
        and could collide). Replicated shards (replica_id != 0) and, on
        processes other than 0, host-resident leaves are skipped so each
        shard is written exactly once across the gang."""
        import jax

        from ray_tpu.checkpoint.async_checkpointer import (
            snapshot_to_host, write_host_snapshot)

        idx = process_index if process_index is not None \
            else jax.process_index()
        pdir = os.path.join(self.root, f"process_{idx}")
        write_host_snapshot(pdir, snapshot_to_host(state, idx))
        return self.root

    def restore(self, target_state):
        """Restore into arrays shaped/sharded like target_state."""
        import json
        import jax
        import numpy as np
        from jax.tree_util import tree_flatten_with_path, tree_unflatten

        entries: Dict[str, list] = {}
        for pname in sorted(os.listdir(self.root)):
            pdir = os.path.join(self.root, pname)
            mf = os.path.join(pdir, "manifest.json")
            if not os.path.exists(mf):
                continue
            with open(mf) as f:
                for e in json.load(f):
                    e["dir"] = pdir
                    entries.setdefault(e["key"], []).append(e)
        leaves, treedef = tree_flatten_with_path(target_state)
        out = []
        for path, leaf in leaves:
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                           for p in path)
            shards = entries.get(key)
            if not shards:
                raise KeyError(f"checkpoint missing {key}")
            full = np.zeros(shards[0]["shape"],
                            dtype=np.dtype(shards[0]["dtype"]))
            for e in shards:
                data = np.load(os.path.join(e["dir"], e["file"]))
                if e["index"] is None:
                    full = data
                else:
                    full[_json_to_index(e["index"])] = data
            if hasattr(leaf, "sharding"):
                out.append(jax.device_put(full, leaf.sharding))
            else:
                out.append(full)
        return tree_unflatten(treedef, out)


def _index_to_json(index):
    return [[s.start, s.stop, s.step] for s in index]


def _json_to_index(idx_json):
    return tuple(slice(a, b, c) for a, b, c in idx_json)
