"""AIR configs.

Reference analogue: python/ray/air/config.py — ScalingConfig:79,
FailureConfig:454, CheckpointConfig:513, RunConfig:642. ScalingConfig gains
TPU-first fields: chips per worker, slice topology, and the MeshSpec axes for
model parallelism inside the SPMD island.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    use_gpu: bool = False
    # resources per gang worker
    resources_per_worker: Optional[Dict[str, float]] = None
    tpu_chips_per_worker: int = 0  # 0 = all chips of the worker's host
    # constrain workers onto hosts of one slice (ICI gang domain)
    tpu_topology: Optional[str] = None
    placement_strategy: str = "PACK"
    # model-parallel axes inside the island (dp fills the remainder)
    mesh: Optional[Dict[str, int]] = None
    trainer_resources: Optional[Dict[str, float]] = None

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = float(self.tpu_chips_per_worker or 1)
        if self.use_gpu and "GPU" not in res:
            res["GPU"] = 1.0
        return res

    @property
    def total_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        per = self.worker_resources()
        for k, v in per.items():
            out[k] = v * self.num_workers
        return out


@dataclass
class FailureConfig:
    max_failures: int = 0  # -1 = infinite; trial restarts from last ckpt
    fail_fast: bool = False


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None  # None = keep all
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False
    # TPU-first addition, consumed by ray_tpu.checkpoint.CheckpointManager:
    # steps divisible by k survive num_to_keep eviction (milestone
    # checkpoints for post-hoc eval on a preemptible pod)
    keep_every_k: int = 0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # local experiment root (local_dir)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(
        default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    log_to_file: bool = False
    # driver-side experiment callbacks (None = default CSV/JSON/TB loggers)
    callbacks: Optional[list] = None
    sync_config: Optional[Any] = None  # tune.syncer.SyncConfig
