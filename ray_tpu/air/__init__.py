"""ray_tpu.air — shared Train/Tune primitives (reference: ray.air)."""

from ray_tpu.air.checkpoint import Checkpoint, ShardedCheckpoint  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig, FailureConfig, RunConfig, ScalingConfig)
from ray_tpu.air import session  # noqa: F401
from ray_tpu.air.session import TrainingResult  # noqa: F401
from ray_tpu.air.preprocessor import (  # noqa: F401
    BatchMapper, Chain, Concatenator, LabelEncoder, MaxAbsScaler,
    MinMaxScaler, Normalizer, OneHotEncoder, OrdinalEncoder,
    Preprocessor, RobustScaler, SimpleImputer, StandardScaler)
from ray_tpu.air.batch_predictor import (  # noqa: F401
    BatchPredictor, JaxPredictor, Predictor)
