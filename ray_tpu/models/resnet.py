"""ResNet family (flax linen), TPU-first.

Benchmark parity target: the reference's ResNet-50/ImageNet AIR benchmark
(reference: release/air_tests/air_benchmarks/mlperf-train/resnet50_ray_air.py)
— torchvision resnet50 under TorchTrainer/DDP. Here the model is native
flax: NHWC layout (TPU conv layout), bfloat16 compute with float32 params
and batch stats, SAME-padded 3x3 stem variant available for CIFAR.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16  # compute dtype; params stay f32
    act: Callable = nn.relu
    small_images: bool = False  # CIFAR stem: 3x3/1 conv, no maxpool
    bn_axis_name: Optional[str] = None  # set under shard_map/pmap for
    # cross-replica sync-BN; None under jit/GSPMD (local-shard stats, the
    # standard large-batch approximation — torch DDP BatchNorm does the same)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 padding="SAME")
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, epsilon=1e-5,
                                 dtype=self.dtype,
                                 axis_name=self.bn_axis_name)
        x = x.astype(self.dtype)
        if self.small_images:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, conv=conv, norm=norm,
                                   act=self.act)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckResNetBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckResNetBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckResNetBlock)


def create_resnet(name: str, num_classes: int, *,
                  small_images: bool = False,
                  dtype=jnp.bfloat16) -> ResNet:
    table = {"resnet18": ResNet18, "resnet34": ResNet34,
             "resnet50": ResNet50, "resnet101": ResNet101,
             "resnet152": ResNet152}
    if name not in table:
        raise ValueError(f"unknown resnet {name!r}; options {sorted(table)}")
    return table[name](num_classes=num_classes, small_images=small_images,
                       dtype=dtype)
