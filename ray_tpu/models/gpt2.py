"""GPT-2 family (flax linen), TPU-first with mesh-aware attention.

Benchmark parity target: the reference's HF GPT-2 fine-tune config
(reference: train/huggingface/huggingface_trainer.py + BASELINE.json
"HF GPT-2 causal-LM fine-tune"). Native flax implementation:

  - bfloat16 activations, f32 params/softmax accumulation
  - attention backend selectable: "flash" (pallas kernel on TPU),
    "ring" (sp-axis ring attention for long context), "reference"
  - weights laid out for the MeshSpec tp rules (qkv fused kernel shards on
    the head dim; out-projection shards the input dim — mesh.py _tp_hint)
  - HF GPT-2 checkpoint import (transformers is in-image) for fine-tune parity
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attention_backend: str = "flash"  # flash | ring | reference
    ring_axis: str = "sp"

    @classmethod
    def small(cls):  # gpt2 124M
        return cls()

    @classmethod
    def medium(cls):
        return cls(n_embd=1024, n_layer=24, n_head=16)

    @classmethod
    def large(cls):
        return cls(n_embd=1280, n_layer=36, n_head=20)

    @classmethod
    def tiny(cls, vocab_size: int = 512):  # tests
        return cls(vocab_size=vocab_size, n_positions=256, n_embd=128,
                   n_layer=2, n_head=4, dtype=jnp.float32,
                   attention_backend="reference")


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True, kv_cache=None,
                 seq_lengths=None, valid=None):
        cfg = self.config
        B, S, E = x.shape
        head_dim = cfg.n_embd // cfg.n_head
        qkv = nn.Dense(3 * cfg.n_embd, dtype=cfg.dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [B,S,E] -> [B,H,S,D]
            return t.reshape(B, S, cfg.n_head, head_dim).transpose(0, 2, 1, 3)

        if kv_cache is not None:
            # incremental decode (docs/LLM_SERVING.md): append this
            # call's kv into the cache (contiguous or paged) and attend
            # the S new queries against the whole cached prefix
            from ray_tpu.ops.attention import cached_attention
            tok = lambda t: t.reshape(B, S, cfg.n_head, head_dim)  # noqa: E731
            y, new_cache = cached_attention(
                tok(q), tok(k), tok(v), kv_cache, seq_lengths,
                valid=valid)
            y = y.reshape(B, S, E)
            y = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj")(y)
            return (nn.Dropout(cfg.dropout)(y, deterministic),
                    new_cache)
        q, k, v = heads(q), heads(k), heads(v)
        if cfg.attention_backend == "ring":
            from ray_tpu.ops.ring_attention import ring_attention
            y = ring_attention(q, k, v, axis_name=cfg.ring_axis, causal=True)
        elif cfg.attention_backend == "flash":
            from ray_tpu.ops.attention import flash_attention
            y = flash_attention(q, k, v, causal=True)
        else:
            from ray_tpu.ops.attention import attention_reference
            y = attention_reference(q, k, v, causal=True)
        y = y.transpose(0, 2, 1, 3).reshape(B, S, E)
        y = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj")(y)
        y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return y


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype, name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj")(h)
        return nn.Dropout(cfg.dropout)(h, deterministic=deterministic)


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True, kv_cache=None,
                 seq_lengths=None, valid=None):
        cfg = self.config
        if kv_cache is not None:
            y, new_cache = CausalSelfAttention(cfg, name="attn")(
                nn.LayerNorm(dtype=jnp.float32, name="ln_1")(x),
                deterministic, kv_cache=kv_cache,
                seq_lengths=seq_lengths, valid=valid)
            x = x + y
            x = x + MLP(cfg, name="mlp")(
                nn.LayerNorm(dtype=jnp.float32, name="ln_2")(x),
                deterministic)
            return x, new_cache
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(dtype=jnp.float32, name="ln_1")(x), deterministic)
        x = x + MLP(cfg, name="mlp")(
            nn.LayerNorm(dtype=jnp.float32, name="ln_2")(x), deterministic)
        return x


class GPT2(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True,
                 positions: Optional[jnp.ndarray] = None,
                 kv_cache=None, seq_lengths=None, valid=None):
        """Full forward (logits) — or, with ``kv_cache``, one
        incremental step: the S tokens of ``input_ids`` are appended to
        per-layer caches (``init_kv_cache`` / the serve LLM engine's
        paged pool) holding ``seq_lengths`` prior tokens, and the
        return value is ``(logits, new_kv_cache)``. Prefill is the
        ``seq_lengths == 0`` case; decode passes one token at a time.
        ``valid`` marks real tokens when S is padded to a bucket."""
        cfg = self.config
        B, S = input_ids.shape
        incremental = kv_cache is not None
        if positions is None:
            if incremental:
                positions = seq_lengths[:, None] + jnp.arange(S)[None, :]
                if valid is not None:
                    positions = jnp.where(valid, positions, 0)
            else:
                positions = jnp.arange(S)[None, :]
        wte = nn.Embed(cfg.vocab_size, cfg.n_embd,
                       dtype=cfg.dtype, name="wte")
        wpe = nn.Embed(cfg.n_positions, cfg.n_embd,
                       dtype=cfg.dtype, name="wpe")
        x = wte(input_ids) + wpe(positions)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        new_caches = []
        for i in range(cfg.n_layer):
            if incremental:
                x, c = Block(cfg, name=f"h_{i}")(
                    x, deterministic, kv_cache=kv_cache[i],
                    seq_lengths=seq_lengths, valid=valid)
                new_caches.append(c)
            else:
                x = Block(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        # weight-tied LM head
        logits = wte.attend(x.astype(jnp.float32))
        return (logits, new_caches) if incremental else logits


def init_kv_cache(cfg: GPT2Config, batch_size: int, max_len: int):
    """Per-layer contiguous KV caches for incremental decode
    ([B, S_max, H, D] token-major — the layout ops.attention's cached
    paths share with the paged pool)."""
    hd = cfg.n_embd // cfg.n_head
    shape = (batch_size, max_len, cfg.n_head, hd)
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layer)]


def causal_lm_loss(logits, labels, ignore_index: int = -100):
    """Next-token cross entropy; labels == input_ids shifted by the caller
    or equal to input_ids (then shifting happens here).

    Written as ``logsumexp - gathered_logit`` rather than
    ``take_along_axis(log_softmax(...))``: the latter materializes the
    full [B, S, V] log-probability array (3.3 GB/step at the GPT-2
    bench shape) only to gather one column per token, while reductions
    and gathers over the raw logits fuse without that round trip.  The
    exp-sum accumulates in f32 even for bf16 logits (bf16 accumulation
    over a 50k vocab loses the loss signal)."""
    shift_logits = logits[:, :-1]
    shift_labels = labels[:, 1:]
    mask = (shift_labels != ignore_index)
    safe = jnp.where(mask, shift_labels, 0)
    m = jax.lax.stop_gradient(jnp.max(shift_logits, axis=-1))
    sumexp = jnp.sum(
        jnp.exp((shift_logits - m[..., None]).astype(jnp.float32)),
        axis=-1)
    lse = m.astype(jnp.float32) + jnp.log(sumexp)
    ll = jnp.take_along_axis(shift_logits, safe[..., None],
                             axis=-1)[..., 0].astype(jnp.float32)
    nll = lse - ll
    total = jnp.sum(nll * mask)
    count = jnp.maximum(jnp.sum(mask), 1)
    return total / count


def load_hf_gpt2_params(model_name: str = "gpt2",
                        config: Optional[GPT2Config] = None):
    """Import HuggingFace GPT-2 weights into this module's param tree
    (fine-tune parity with the reference's HF trainer path)."""
    from transformers import GPT2LMHeadModel
    hf = GPT2LMHeadModel.from_pretrained(model_name)
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    cfg = config or GPT2Config()
    p: dict = {"wte": {"embedding": sd["transformer.wte.weight"]},
               "wpe": {"embedding": sd["transformer.wpe.weight"]},
               "ln_f": {"scale": sd["transformer.ln_f.weight"],
                        "bias": sd["transformer.ln_f.bias"]}}
    for i in range(cfg.n_layer):
        hfp = f"transformer.h.{i}."
        p[f"h_{i}"] = {
            "ln_1": {"scale": sd[hfp + "ln_1.weight"],
                     "bias": sd[hfp + "ln_1.bias"]},
            "ln_2": {"scale": sd[hfp + "ln_2.weight"],
                     "bias": sd[hfp + "ln_2.bias"]},
            "attn": {
                "c_attn": {"kernel": sd[hfp + "attn.c_attn.weight"],
                           "bias": sd[hfp + "attn.c_attn.bias"]},
                "c_proj": {"kernel": sd[hfp + "attn.c_proj.weight"],
                           "bias": sd[hfp + "attn.c_proj.bias"]},
            },
            "mlp": {
                "c_fc": {"kernel": sd[hfp + "mlp.c_fc.weight"],
                         "bias": sd[hfp + "mlp.c_fc.bias"]},
                "c_proj": {"kernel": sd[hfp + "mlp.c_proj.weight"],
                           "bias": sd[hfp + "mlp.c_proj.bias"]},
            },
        }
    return jax.tree_util.tree_map(jnp.asarray, {"params": p})
