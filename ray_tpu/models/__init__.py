"""ray_tpu.models — flax model families with TPU-first layouts."""

from ray_tpu.models.resnet import create_resnet  # noqa: F401
from ray_tpu.models.gpt2 import GPT2Config  # noqa: F401
from ray_tpu.models.llama import (  # noqa: F401
    LlamaConfig, LlamaModel, causal_lm_loss, import_hf_llama)
