"""Llama-family decoder (flax linen), TPU-first.

The modern-decoder counterpart to models/gpt2.py (reference analogue:
the reference serves this family through HF inside its Train workers —
train/huggingface/huggingface_trainer.py; there is no in-tree CUDA
Llama, so this module is the TPU-native implementation of the same
capability):

  - RMSNorm (f32 accumulation), rotary position embeddings, SwiGLU MLP,
    grouped-query attention (n_kv_heads <= n_heads), no biases
  - bfloat16 activations, f32 params; attention backend selectable:
    "flash" (pallas), "ring" (sp-axis ring attention for long
    context), "reference"
  - weight layouts follow the MeshSpec tp rules (fused qkv shards the
    head dim, out/down projections shard the input dim) like gpt2.py
  - HF Llama checkpoint import via transformers when weights are local
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32          # < n_heads => grouped-query attention
    ffn_hidden: Optional[int] = None  # default: SwiGLU 8/3 * dim rounded
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention_backend: str = "flash"  # flash | ring | reference
    ring_axis: str = "sp"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def ffn_dim(self) -> int:
        if self.ffn_hidden is not None:
            return self.ffn_hidden
        # llama-2 sizing: 2/3 * 4d, rounded up to a multiple of 256
        h = int(2 * (4 * self.dim) / 3)
        return (h + 255) // 256 * 256

    @classmethod
    def llama2_7b(cls):
        return cls()

    @classmethod
    def llama2_13b(cls):
        return cls(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40)

    @classmethod
    def tiny(cls, vocab_size: int = 512):  # tests: GQA exercised
        return cls(vocab_size=vocab_size, max_seq_len=256, dim=128,
                   n_layers=2, n_heads=8, n_kv_heads=2,
                   dtype=jnp.float32, attention_backend="reference")


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        # f32 accumulation regardless of activation dtype
        scale = self.param("weight", nn.initializers.ones, (x.shape[-1],))
        xf = x.astype(jnp.float32)
        normed = xf * jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(x.dtype)


def rope_frequencies(head_dim: int, max_seq_len: int,
                     theta: float) -> jnp.ndarray:
    """[S, D/2] complex rotation angles, precomputed once per model."""
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    t = np.arange(max_seq_len)
    freqs = np.outer(t, inv)                    # [S, D/2]
    return jnp.asarray(np.stack([np.cos(freqs), np.sin(freqs)], -1),
                       jnp.float32)             # [S, D/2, 2]


def apply_rope(x: jnp.ndarray, freqs: jnp.ndarray,
               positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """x: [B,H,S,D]; rotate (first-half, second-half) feature pairs by
    position angle — the rotate-half convention HF Llama checkpoints
    are permuted for, so imported weights work unmodified.

    ``positions`` ([B, S] int32, optional) gives each token its
    absolute position explicitly — incremental decode rotates the new
    tokens by their true offsets instead of 0..S-1."""
    B, H, S, D = x.shape
    if positions is None:
        cos = freqs[:S, :, 0][None, None]       # [1,1,S,D/2]
        sin = freqs[:S, :, 1][None, None]
    else:
        per = jnp.take(freqs, positions, axis=0)  # [B,S,D/2,2]
        cos = per[..., 0][:, None]              # [B,1,S,D/2]
        sin = per[..., 1][:, None]
    x1, x2 = x[..., :D // 2], x[..., D // 2:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    r1 = xf1 * cos - xf2 * sin
    r2 = xf2 * cos + xf1 * sin
    return jnp.concatenate([r1, r2], axis=-1).astype(x.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, freqs, kv_cache=None, seq_lengths=None,
                 valid=None, positions=None):
        cfg = self.config
        B, S, E = x.shape
        hd = cfg.head_dim
        q = nn.Dense(cfg.n_heads * hd, use_bias=False, dtype=cfg.dtype,
                     name="wq")(x)
        k = nn.Dense(cfg.n_kv_heads * hd, use_bias=False, dtype=cfg.dtype,
                     name="wk")(x)
        v = nn.Dense(cfg.n_kv_heads * hd, use_bias=False, dtype=cfg.dtype,
                     name="wv")(x)
        q = q.reshape(B, S, cfg.n_heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, cfg.n_kv_heads, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, freqs, positions)
        k = apply_rope(k, freqs, positions)
        if kv_cache is not None:
            # incremental decode: cache holds the UN-replicated kv
            # heads (GQA broadcast happens inside decode_attention)
            from ray_tpu.ops.attention import cached_attention
            y, new_cache = cached_attention(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), kv_cache, seq_lengths,
                valid=valid)
            y = y.reshape(B, S, cfg.n_heads * hd)
            return (nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                             name="wo")(y), new_cache)
        if cfg.n_kv_heads != cfg.n_heads:
            # grouped-query: broadcast each kv head over its query group
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        if cfg.attention_backend == "ring":
            from ray_tpu.ops.ring_attention import ring_attention
            y = ring_attention(q, k, v, axis_name=cfg.ring_axis,
                               causal=True)
        elif cfg.attention_backend == "flash":
            from ray_tpu.ops.attention import flash_attention
            y = flash_attention(q, k, v, causal=True)
        else:
            from ray_tpu.ops.attention import attention_reference
            y = attention_reference(q, k, v, causal=True)
        y = y.transpose(0, 2, 1, 3).reshape(B, S, cfg.n_heads * hd)
        return nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                        name="wo")(y)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        gate = nn.Dense(cfg.ffn_dim, use_bias=False, dtype=cfg.dtype,
                        name="w1")(x)
        up = nn.Dense(cfg.ffn_dim, use_bias=False, dtype=cfg.dtype,
                      name="w3")(x)
        return nn.Dense(cfg.dim, use_bias=False, dtype=cfg.dtype,
                        name="w2")(nn.silu(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, freqs, kv_cache=None, seq_lengths=None,
                 valid=None, positions=None):
        cfg = self.config
        if kv_cache is not None:
            y, new_cache = LlamaAttention(cfg, name="attention")(
                RMSNorm(cfg.norm_eps, name="attention_norm")(x), freqs,
                kv_cache=kv_cache, seq_lengths=seq_lengths,
                valid=valid, positions=positions)
            x = x + y
            x = x + LlamaMLP(cfg, name="feed_forward")(
                RMSNorm(cfg.norm_eps, name="ffn_norm")(x))
            return x, new_cache
        x = x + LlamaAttention(cfg, name="attention")(
            RMSNorm(cfg.norm_eps, name="attention_norm")(x), freqs)
        x = x + LlamaMLP(cfg, name="feed_forward")(
            RMSNorm(cfg.norm_eps, name="ffn_norm")(x))
        return x


class LlamaModel(nn.Module):
    """Decoder LM: tokens -> logits (f32)."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, kv_cache=None, seq_lengths=None,
                 valid=None):
        """Full forward — or, with ``kv_cache``, one incremental step
        (prefill at ``seq_lengths == 0``, then single-token decodes):
        tokens are appended to the per-layer caches and rotated by
        their TRUE absolute positions; returns ``(logits, new_cache)``.
        ``valid`` marks real tokens when S is padded to a bucket."""
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.dim,
                     dtype=cfg.dtype, name="tok_embeddings")(input_ids)
        freqs = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                 cfg.rope_theta)
        incremental = kv_cache is not None
        positions = None
        if incremental:
            S = input_ids.shape[1]
            positions = seq_lengths[:, None] + jnp.arange(S)[None, :]
            if valid is not None:
                positions = jnp.where(valid, positions, 0)
        new_caches = []
        for i in range(cfg.n_layers):
            if incremental:
                x, c = LlamaBlock(cfg, name=f"layers_{i}")(
                    x, freqs, kv_cache=kv_cache[i],
                    seq_lengths=seq_lengths, valid=valid,
                    positions=positions)
                new_caches.append(c)
            else:
                x = LlamaBlock(cfg, name=f"layers_{i}")(x, freqs)
        x = RMSNorm(cfg.norm_eps, name="norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=jnp.float32, name="output")(x)
        return (logits, new_caches) if incremental else logits


def init_kv_cache(cfg: LlamaConfig, batch_size: int, max_len: int):
    """Per-layer contiguous KV caches ([B, S_max, Hkv, D] token-major,
    GQA: the un-replicated kv heads) for incremental decode."""
    shape = (batch_size, max_len, cfg.n_kv_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def causal_lm_loss(logits, input_ids):
    """Next-token cross-entropy (f32), mean over B*(S-1)."""
    targets = input_ids[:, 1:]
    logits = logits[:, :-1].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def import_hf_llama(model_name_or_path: str, cfg: LlamaConfig):
    """Map a HF LlamaForCausalLM state dict onto this module's params
    (gated on transformers + local weights; mirrors
    models/gpt2.py's HF import)."""
    import torch  # noqa: F401 — transformers loads via torch
    from transformers import LlamaForCausalLM
    hf = LlamaForCausalLM.from_pretrained(model_name_or_path)
    sd = {k: v.detach().cpu().numpy() for k, v in hf.state_dict().items()}

    def dense(w):  # torch [out,in] -> flax kernel [in,out]
        return jnp.asarray(w.T)

    params = {"tok_embeddings": {
        "embedding": jnp.asarray(sd["model.embed_tokens.weight"])}}
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        params[f"layers_{i}"] = {
            "attention_norm": {
                "weight": jnp.asarray(sd[p + "input_layernorm.weight"])},
            "ffn_norm": {
                "weight": jnp.asarray(
                    sd[p + "post_attention_layernorm.weight"])},
            "attention": {
                "wq": {"kernel": dense(sd[p + "self_attn.q_proj.weight"])},
                "wk": {"kernel": dense(sd[p + "self_attn.k_proj.weight"])},
                "wv": {"kernel": dense(sd[p + "self_attn.v_proj.weight"])},
                "wo": {"kernel": dense(sd[p + "self_attn.o_proj.weight"])},
            },
            "feed_forward": {
                "w1": {"kernel": dense(sd[p + "mlp.gate_proj.weight"])},
                "w3": {"kernel": dense(sd[p + "mlp.up_proj.weight"])},
                "w2": {"kernel": dense(sd[p + "mlp.down_proj.weight"])},
            },
        }
    params["norm"] = {"weight": jnp.asarray(sd["model.norm.weight"])}
    params["output"] = {"kernel": dense(sd["lm_head.weight"])}
    return {"params": params}
