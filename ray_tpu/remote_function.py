"""``@ray_tpu.remote`` functions.

Reference analogue: python/ray/remote_function.py (RemoteFunction._remote:239
→ core_worker.submit_task:385). The function is exported to GCS KV once and
referenced by key in every task spec.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private.worker import ObjectRef, global_worker
from ray_tpu.common.options import validate_options

# bound lazily (ray_tpu.util imports back into the package); cached —
# a per-call ``from ... import client_mode`` showed up in submit profiles
_client_mode = None


class RemoteFunction:
    def __init__(self, fn, default_opts: Dict[str, Any]):
        self._fn = fn
        self._default_opts = validate_options(default_opts, is_actor=False)
        self._fn_key: Optional[str] = None
        self._fn_key_mgr = None  # function manager the key was exported to
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__!r} cannot be called "
            f"directly; use .remote()")

    def options(self, **opts) -> "_BoundRemoteFunction":
        merged = {**self._default_opts,
                  **validate_options(opts, is_actor=False)}
        return _BoundRemoteFunction(self, merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_opts)

    def remote_batch(self, arg_tuples):
        """Submit many invocations in one go: ``arg_tuples`` is an
        iterable of positional-arg tuples; returns a list of refs (or
        ref-lists when num_returns > 1). Amortizes per-call overhead —
        the >=10k tasks/s submission path (reference analogue: the
        batched submission the reference's scalability envelope relies
        on, release/benchmarks/README.md)."""
        return self._remote_batch(arg_tuples, self._default_opts)

    def bind(self, *args, **kwargs):
        """DAG authoring (reference: python/ray/dag FunctionNode)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs, self._default_opts)

    def _remote(self, args, kwargs, opts: Dict[str, Any]):
        global _client_mode
        if _client_mode is None:
            from ray_tpu.util.client.worker import client_mode
            _client_mode = client_mode
        c = _client_mode()
        if c is not None and c.connected:
            return c.submit_fn(self._fn, args, kwargs, opts)
        w = global_worker()
        if self._fn_key is None or self._fn_key_mgr is not w.function_manager:
            # re-export after a cluster restart: the key cache is only
            # valid for the GCS it was exported to
            self._fn_key = w.function_manager.export(self._fn, kind="fn")
            self._fn_key_mgr = w.function_manager
        refs = w.submit_task(self._fn_key, self._fn.__name__, args, kwargs,
                             opts)
        num_returns = opts.get("num_returns")
        if num_returns is None or num_returns == 1:
            return refs[0]
        return refs

    def _remote_batch(self, arg_tuples, opts: Dict[str, Any]):
        w = global_worker()
        if self._fn_key is None or self._fn_key_mgr is not w.function_manager:
            self._fn_key = w.function_manager.export(self._fn, kind="fn")
            self._fn_key_mgr = w.function_manager
        ref_lists = w.submit_task_batch(self._fn_key, self._fn.__name__,
                                        arg_tuples, opts)
        num_returns = opts.get("num_returns")
        if num_returns is None or num_returns == 1:
            return [refs[0] for refs in ref_lists]
        return ref_lists


class _BoundRemoteFunction:
    def __init__(self, remote_fn: RemoteFunction, opts: Dict[str, Any]):
        self._remote_fn = remote_fn
        self._opts = opts

    def remote(self, *args, **kwargs):
        return self._remote_fn._remote(args, kwargs, self._opts)

    def remote_batch(self, arg_tuples):
        return self._remote_fn._remote_batch(arg_tuples, self._opts)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self._remote_fn, args, kwargs, self._opts)
