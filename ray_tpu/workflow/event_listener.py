"""Workflow event listeners.

Reference analogue: workflow/event_listener.py (EventListener ABC +
TimerListener) and api.wait_for_event — a workflow step that completes
when an EXTERNAL event arrives.  The event's payload is checkpointed
exactly like a step result, so a resumed workflow does not wait for
(or observe) the event twice.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any


class EventListener:
    """Subclass and implement ``poll_for_event`` (async).  The listener
    is constructed INSIDE the event step's worker, so it must be
    picklable by class reference (module-level class)."""

    async def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError

    async def event_checkpointed(self, event: Any) -> None:
        """Optional commit hook: called after the event's payload has
        been durably checkpointed (e.g. ack a message queue)."""


class TimerListener(EventListener):
    """Fires at an absolute unix timestamp (reference:
    event_listener.TimerListener)."""

    async def poll_for_event(self, fire_at: float) -> float:
        await asyncio.sleep(max(0.0, fire_at - time.time()))
        return fire_at


def _poll_listener(listener_cls, args, kwargs):
    """Step body: construct the listener and wait for its event."""
    listener = listener_cls()
    return asyncio.run(listener.poll_for_event(*args, **kwargs))


def _ack_listener(listener_cls, event):
    """Post-checkpoint commit hook (see api._post_commit)."""
    listener = listener_cls()
    asyncio.run(listener.event_checkpointed(event))
