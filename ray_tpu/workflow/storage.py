"""Workflow persistence.

Reference analogue: workflow/workflow_storage.py (every step result
persisted on ``ray.storage`` for exactly-once resume). Layout:
``<root>/<workflow_id>/steps/<step_id>.pkl`` + ``status.json``.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
from typing import Any, Dict, List, Optional

_DEFAULT_ROOT = os.path.join(tempfile.gettempdir(), "ray_tpu_workflows")
_storage_root = os.environ.get("RTPU_WORKFLOW_STORAGE", _DEFAULT_ROOT)


def set_storage(root: str):
    global _storage_root
    _storage_root = root


def get_storage() -> str:
    # explicit workflow setting wins; otherwise the cluster-wide
    # ray_tpu.init(storage=...) root hosts a workflows/ subtree
    if _storage_root == _DEFAULT_ROOT and \
            "RTPU_WORKFLOW_STORAGE" not in os.environ:
        try:
            from ray_tpu._private.storage import get_storage_root
            root = get_storage_root()
            if root:
                return os.path.join(root, "workflows")
        except Exception:
            pass
    return _storage_root


class WorkflowStorage:
    def __init__(self, workflow_id: str,
                 root: Optional[str] = None):
        self.workflow_id = workflow_id
        # get_storage (NOT the raw module global): run/resume/status must
        # agree on the cluster-wide storage root
        self.dir = os.path.join(root or get_storage(), workflow_id)
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    # atomic write: temp file + rename
    def _write(self, path: str, data: bytes):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def save_step_result(self, step_id: str, value: Any):
        self._write(os.path.join(self.dir, "steps", f"{step_id}.pkl"),
                    pickle.dumps(value))

    def has_step_result(self, step_id: str) -> bool:
        return os.path.exists(
            os.path.join(self.dir, "steps", f"{step_id}.pkl"))

    def load_step_result(self, step_id: str) -> Any:
        with open(os.path.join(self.dir, "steps", f"{step_id}.pkl"),
                  "rb") as f:
            return pickle.load(f)

    def save_status(self, status: str,
                    extra: Optional[Dict[str, Any]] = None):
        doc = {"workflow_id": self.workflow_id, "status": status,
               **(extra or {})}
        self._write(os.path.join(self.dir, "status.json"),
                    json.dumps(doc).encode())

    def load_status(self) -> Optional[Dict[str, Any]]:
        p = os.path.join(self.dir, "status.json")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    def touch_claim(self):
        """Liveness stamp from the executing driver (refreshed between
        steps); resume_all only resumes RUNNING workflows whose claim
        has gone stale."""
        p = os.path.join(self.dir, "claim")
        with open(p, "w") as f:
            f.write(str(os.getpid()))

    def claim_age(self) -> Optional[float]:
        p = os.path.join(self.dir, "claim")
        try:
            import time
            return time.time() - os.path.getmtime(p)
        except OSError:
            return None

    def save_dag(self, dag_bytes: bytes):
        self._write(os.path.join(self.dir, "dag.pkl"), dag_bytes)

    def load_dag(self) -> Optional[bytes]:
        p = os.path.join(self.dir, "dag.pkl")
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()


def list_workflows(root: Optional[str] = None) -> List[Dict[str, Any]]:
    root = root or get_storage()
    out = []
    if not os.path.isdir(root):
        return out
    for wid in sorted(os.listdir(root)):
        st = WorkflowStorage(wid, root).load_status()
        if st:
            out.append(st)
    return out
