"""Workflow engine: durable DAG execution with resume.

Reference analogue: workflow/api.py (run:120, run_async:166),
workflow_executor.py, workflow_access.py. A DAG authored with
``.bind()`` (ray_tpu.dag) executes step-by-step; every step's result is
persisted before dependents run, so a crashed workflow resumes from the
last completed step (exactly-once per step, assuming idempotent steps —
same contract as the reference).
"""

from __future__ import annotations

import hashlib
import uuid
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode
from ray_tpu.workflow.storage import (WorkflowStorage, list_workflows,
                                      set_storage)


def _arg_digest(a: Any) -> str:
    """Process-stable digest of a static argument. Pickle bytes (unlike
    repr) don't embed memory addresses, so resume in a new process
    computes identical step ids."""
    try:
        return hashlib.sha1(cloudpickle.dumps(a)).hexdigest()[:16]
    except Exception:
        return repr(a)


def _step_id(node: DAGNode, child_ids: List[str]) -> str:
    """Deterministic content-based step id: function name + static
    args/kwargs digests + child step ids, so resume matches steps
    across processes."""
    if isinstance(node, FunctionNode):
        fn = node._remote_fn._fn
        base = f"{fn.__module__}.{fn.__qualname__}"
    else:
        base = type(node).__name__
    static_args = [_arg_digest(a) for a in node._bound_args
                   if not isinstance(a, DAGNode)]
    static_kwargs = [f"{k}={_arg_digest(v)}"
                     for k, v in sorted(node._bound_kwargs.items())
                     if not isinstance(v, DAGNode)]
    payload = "|".join([base, *static_args, *static_kwargs, *child_ids])
    return (base.split(".")[-1] + "-"
            + hashlib.sha1(payload.encode()).hexdigest()[:10])


class WorkflowCancelledError(Exception):
    """The workflow was cancelled (workflow.cancel) between steps."""


class _StepExec:
    """Recursive executor materializing one step at a time (children
    first), checkpointing each result."""

    def __init__(self, storage: WorkflowStorage, input_value: Any):
        self.storage = storage
        self.input_value = input_value
        self._memo: Dict[int, Any] = {}

    def _check_cancelled(self):
        st = self.storage.load_status()
        if st and st.get("status") == "CANCELED":
            raise WorkflowCancelledError(self.storage.workflow_id)
        # liveness claim: refreshed before every step launch so
        # resume_all can tell a crashed RUNNING workflow (stale claim)
        # from one actively executing in a live driver
        self.storage.touch_claim()

    def run(self, node: Any) -> Any:
        if not isinstance(node, DAGNode):
            return node
        key = id(node)
        if key in self._memo:
            return self._memo[key]
        if isinstance(node, InputNode):
            value = self.input_value
            self._memo[key] = value
            return value
        child_ids: List[str] = []
        resolved_args = []
        for a in node._bound_args:
            if isinstance(a, DAGNode):
                v, cid = self._run_child(a)
                resolved_args.append(v)
                child_ids.append(cid)
            else:
                resolved_args.append(a)
        resolved_kwargs = {}
        for k, a in node._bound_kwargs.items():
            if isinstance(a, DAGNode):
                v, cid = self._run_child(a)
                resolved_kwargs[k] = v
                child_ids.append(cid)
            else:
                resolved_kwargs[k] = a
        sid = _step_id(node, child_ids)
        if self.storage.has_step_result(sid):
            value = self.storage.load_step_result(sid)
            # re-run the post-commit hook: a crash between checkpoint
            # and ack must re-ack on resume (at-least-once ack — the
            # __acked marker inside makes the completed case a no-op)
            self._post_commit(node, sid, value)
        else:
            self._check_cancelled()
            if isinstance(node, FunctionNode):
                ref = node._remote_fn._remote(
                    tuple(resolved_args), resolved_kwargs, node._opts)
                value = ray_tpu.get(ref)
            else:
                raise TypeError(
                    f"workflows support function DAG nodes, got "
                    f"{type(node).__name__} (actor nodes are not "
                    f"durable)")
            self.storage.save_step_result(sid, value)
            self._post_commit(node, sid, value)
        self._memo[key] = value
        return value

    def _post_commit(self, node, sid: str, value: Any):
        """Event steps: ack the listener AFTER the payload is durable
        (reference: event_listener.event_checkpointed).  The ack is
        recorded so a resume doesn't re-ack a completed event; a crash
        between checkpoint and ack re-acks on resume (at-least-once
        ack, exactly-once payload — the reference's contract)."""
        listener_cls = getattr(node, "_event_listener", None)
        if listener_cls is None:
            return
        ack_id = sid + "__acked"
        if self.storage.has_step_result(ack_id):
            return
        from ray_tpu.workflow.event_listener import _ack_listener
        _ack_listener(listener_cls, value)
        self.storage.save_step_result(ack_id, True)

    def _run_child(self, node: DAGNode):
        if not hasattr(self, "_fp_cache"):
            self._fp_cache = {}
        return self.run(node), _node_fingerprint(node, self._fp_cache)


def _node_fingerprint(node: DAGNode, _memo: Optional[Dict[int, str]] = None
                      ) -> str:
    # memoized by node identity: diamond DAGs would otherwise cost
    # exponential re-hashing of shared subgraphs
    _memo = _memo if _memo is not None else {}
    key = id(node)
    if key in _memo:
        return _memo[key]
    child_ids = [_node_fingerprint(c, _memo) for c in node._children()]
    fp = "input" if isinstance(node, InputNode) else _step_id(
        node, child_ids)
    _memo[key] = fp
    return fp


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Execute a workflow to completion; resumable by workflow_id
    (reference: workflow.run api.py:120)."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    storage = WorkflowStorage(workflow_id)
    storage.save_status("RUNNING")
    try:
        storage.save_dag(cloudpickle.dumps((dag, input_value)))
    except Exception:
        pass  # non-picklable DAGs can still run, just not resume cold
    # background liveness claim: per-step touches alone go stale inside
    # any step longer than resume_all's freshness window, making a LIVE
    # workflow look crashed (double-run)
    import threading
    stop_claim = threading.Event()

    def _claim_loop():
        while not stop_claim.wait(3.0):
            try:
                storage.touch_claim()
            except OSError:
                pass
    storage.touch_claim()
    claimer = threading.Thread(target=_claim_loop, daemon=True)
    claimer.start()
    try:
        result = _StepExec(storage, input_value).run(dag)
        st = storage.load_status()
        if st and st.get("status") == "CANCELED":
            # cancelled during the final step: honor the cancel —
            # a CANCELED -> SUCCESSFUL transition must not exist
            raise WorkflowCancelledError(workflow_id)
        storage.save_step_result("__result__", result)
        storage.save_status("SUCCESSFUL")
        return result
    except WorkflowCancelledError:
        raise  # status already CANCELED — don't overwrite with FAILED
    except Exception as e:
        storage.save_status("FAILED", {"error": repr(e)})
        raise
    finally:
        stop_claim.set()


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None):
    """Run in a background task; returns an ObjectRef of the result."""
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    blob = cloudpickle.dumps((dag, input_value))
    from ray_tpu.workflow.storage import get_storage
    storage_root = get_storage()

    @ray_tpu.remote
    def _driver(blob, wid, root):
        import cloudpickle as cp
        from ray_tpu.workflow import api as wf_api
        from ray_tpu.workflow.storage import set_storage as _set
        # the worker process has its own module global; without this the
        # workflow persists to the default root and the driver's
        # get_status/resume can't find it
        _set(root)
        d, iv = cp.loads(blob)
        return wf_api.run(d, workflow_id=wid, input_value=iv)

    return _driver.remote(blob, workflow_id, storage_root)


def resume(workflow_id: str) -> Any:
    """Re-run a workflow from storage; completed steps are skipped."""
    storage = WorkflowStorage(workflow_id)
    if storage.has_step_result("__result__"):
        return storage.load_step_result("__result__")
    st = storage.load_status()
    if st and st.get("status") == "CANCELED":
        raise WorkflowCancelledError(
            f"workflow {workflow_id!r} was cancelled; resuming would "
            "silently undo the cancellation")
    blob = storage.load_dag()
    if blob is None:
        raise ValueError(f"workflow {workflow_id!r} has no persisted DAG")
    dag, input_value = cloudpickle.loads(blob)
    return run(dag, workflow_id=workflow_id, input_value=input_value)


def get_status(workflow_id: str) -> Optional[str]:
    st = WorkflowStorage(workflow_id).load_status()
    return st["status"] if st else None


def get_output(workflow_id: str) -> Any:
    storage = WorkflowStorage(workflow_id)
    if not storage.has_step_result("__result__"):
        raise ValueError(f"workflow {workflow_id!r} has no output yet")
    return storage.load_step_result("__result__")


def cancel(workflow_id: str) -> bool:
    """Stop a running workflow between steps (reference:
    workflow/api.py cancel — the executor checks before every step
    launch and raises WorkflowCancelledError)."""
    storage = WorkflowStorage(workflow_id)
    st = storage.load_status()
    if st is None or st["status"] in ("SUCCESSFUL", "FAILED", "CANCELED"):
        return False
    storage.save_status("CANCELED")
    return True


def resume_all() -> List[str]:
    """Resume every workflow left RUNNING by crashed drivers, through
    the management actor (reference: workflow_access.py:88)."""
    from ray_tpu.workflow.workflow_access import get_management_actor
    actor = get_management_actor()
    return ray_tpu.get(actor.resume_all.remote())


def list_all(status_filter: Optional[str] = None) -> List[Dict[str, Any]]:
    """Status rows for every persisted workflow."""
    rows = list_workflows()
    if status_filter:
        rows = [r for r in rows if r.get("status") == status_filter]
    return rows


def wait_for_event(listener_cls, *args, **kwargs) -> DAGNode:
    """A DAG node that completes when the listener's event arrives; the
    event payload is checkpointed like any step result, and the
    listener's ``event_checkpointed`` ack runs after that durable write
    (reference: api.wait_for_event + event_listener.py)."""
    from ray_tpu.workflow.event_listener import (EventListener,
                                                 _poll_listener)
    if not (isinstance(listener_cls, type)
            and issubclass(listener_cls, EventListener)):
        raise TypeError("wait_for_event expects an EventListener "
                        f"subclass, got {listener_cls!r}")

    @ray_tpu.remote
    def _event_step(cls, a, kw):
        return _poll_listener(cls, a, kw)

    node = _event_step.bind(listener_cls, list(args), kwargs)
    node._event_listener = listener_cls
    return node


__all__ = ["run", "run_async", "resume", "get_status", "get_output",
           "cancel", "resume_all", "list_all", "wait_for_event",
           "list_workflows", "set_storage", "WorkflowCancelledError"]
