"""Workflow management actor.

Reference analogue: workflow/workflow_access.py:88
(WorkflowManagementActor) — one named detached actor per cluster owns
workflow lifecycle: submitting runs, status/list queries, cancellation,
and crash recovery (resume_all).  Storage stays the source of truth
(steps/status on the cluster storage root); the actor adds the LIVE
view (what is currently executing) and a single place to drive
recovery from.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu

MANAGEMENT_ACTOR_NAME = "__workflow_management_actor__"


def _actor_name_for_root(root: str) -> str:
    """One management actor PER STORAGE ROOT: a single global actor
    pinned to its creation-time root would answer queries for callers
    using a different set_storage() root from the wrong tree."""
    import hashlib
    return MANAGEMENT_ACTOR_NAME + hashlib.sha1(
        root.encode()).hexdigest()[:8]


@ray_tpu.remote
class WorkflowManagementActor:
    """Cluster-singleton bookkeeping for workflows (detached, named)."""

    def __init__(self, storage_root: str):
        from ray_tpu.workflow.storage import set_storage
        set_storage(storage_root)
        self._storage_root = storage_root
        self._running: Dict[str, Any] = {}  # workflow_id -> ObjectRef

    def submit(self, blob: bytes, workflow_id: str) -> str:
        """Start a pickled (dag, input) workflow asynchronously."""
        ref = _workflow_driver.remote(blob, workflow_id,
                                      self._storage_root)
        self._running[workflow_id] = ref
        return workflow_id

    def get_output_ref(self, workflow_id: str):
        """ObjectRef of a run submitted through this actor (wrapped in
        a list so the caller receives the ref, not its value)."""
        ref = self._running.get(workflow_id)
        return [ref] if ref is not None else None

    def get_status(self, workflow_id: str) -> Optional[str]:
        from ray_tpu.workflow.storage import WorkflowStorage
        st = WorkflowStorage(workflow_id, self._storage_root).load_status()
        return st["status"] if st else None

    def list_all(self, status_filter: Optional[str] = None
                 ) -> List[Dict[str, Any]]:
        from ray_tpu.workflow import api
        return api.list_all(status_filter)  # single source of truth

    def cancel(self, workflow_id: str) -> bool:
        """Mark CANCELED; the executor checks between steps and stops.
        (reference: workflow_access cancel + the executor's
        per-step cancellation check)."""
        from ray_tpu.workflow import api
        ok = api.cancel(workflow_id)
        self._running.pop(workflow_id, None)
        return ok

    # a claim younger than this means a live driver is executing the
    # workflow right now — resuming it would double-run steps
    _CLAIM_FRESH_S = 10.0

    def _prune_running(self):
        """Drop finished/crashed driver refs: a dead entry would make
        resume_all skip its workflow forever, and the retained refs pin
        results in the object store."""
        for wid, ref in list(self._running.items()):
            ready, _ = ray_tpu.wait([ref], timeout=0)
            if ready:
                self._running.pop(wid, None)

    def resume_all(self) -> List[str]:
        """Restart every workflow left RUNNING by a CRASHED driver —
        live ones (fresh liveness claim) are left alone."""
        from ray_tpu.workflow.storage import (WorkflowStorage,
                                              list_workflows)
        self._prune_running()
        resumed = []
        for row in list_workflows(self._storage_root):
            wid = row.get("workflow_id")
            if row.get("status") != "RUNNING" or wid in self._running:
                continue
            storage = WorkflowStorage(wid, self._storage_root)
            age = storage.claim_age()
            if age is not None and age < self._CLAIM_FRESH_S:
                continue  # an alive executor owns it
            blob = storage.load_dag()
            if blob is None:
                continue
            self._running[wid] = _workflow_driver.remote(
                blob, wid, self._storage_root)
            resumed.append(wid)
        return resumed

    def ping(self) -> str:
        return "ok"


@ray_tpu.remote(max_retries=0)
def _workflow_driver(blob: bytes, workflow_id: str, storage_root: str):
    import cloudpickle as cp

    from ray_tpu.workflow import api as wf_api
    from ray_tpu.workflow.storage import set_storage
    set_storage(storage_root)
    dag, input_value = cp.loads(blob)
    return wf_api.run(dag, workflow_id=workflow_id,
                      input_value=input_value)


def get_management_actor():
    """The management actor for the CURRENT storage root, creating it
    on first use."""
    from ray_tpu.workflow.storage import get_storage
    root = get_storage()
    name = _actor_name_for_root(root)
    try:
        return ray_tpu.get_actor(name)
    except Exception:
        pass
    try:
        return WorkflowManagementActor.options(
            name=name, lifetime="detached").remote(root)
    except Exception:
        # creation raced another driver — the name now resolves
        return ray_tpu.get_actor(name)
