"""ray_tpu.workflow — durable DAG execution (reference: workflow/)."""

from ray_tpu.workflow.api import (WorkflowCancelledError, cancel,
                                  get_output, get_status, list_all,
                                  list_workflows, resume, resume_all, run,
                                  run_async, set_storage, wait_for_event)
from ray_tpu.workflow.event_listener import EventListener, TimerListener

__all__ = ["run", "run_async", "resume", "resume_all", "get_status",
           "get_output", "cancel", "list_all", "wait_for_event",
           "list_workflows", "set_storage", "WorkflowCancelledError",
           "EventListener", "TimerListener"]
