"""ray_tpu.workflow — durable DAG execution (reference: workflow/)."""

from ray_tpu.workflow.api import (get_output, get_status, list_workflows,
                                  resume, run, run_async, set_storage)

__all__ = ["run", "run_async", "resume", "get_status", "get_output",
           "list_workflows", "set_storage"]
