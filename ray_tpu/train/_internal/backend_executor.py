"""BackendExecutor: drives a gang of TrainWorkers through one training run.

Reference analogue: train/_internal/backend_executor.py:42 — start:93 spawns
the WorkerGroup, start_training:314 installs per-worker sessions with
world/local/node ranks and launches train_func threads, get_next_results:411
streams result rounds. The backend here is JAX: island formation is
jax.distributed over a coordinator brokered between workers (replacing NCCL
process groups), and each worker's chips surface via TPU_VISIBLE_CHIPS.
"""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.session import TrainingResult
from ray_tpu.train._internal.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(self, scaling_config, backend_config=None):
        self.scaling = scaling_config
        self.backend_config = backend_config
        self.worker_group: Optional[WorkerGroup] = None

    def start(self):
        try:
            self.worker_group = WorkerGroup(
                num_workers=self.scaling.num_workers,
                resources_per_worker=self.scaling.worker_resources(),
                placement_strategy=self.scaling.placement_strategy,
                tpu_topology=self.scaling.tpu_topology)
            self._setup_backend()
        except TrainingFailedError:
            raise
        except Exception as e:
            # gang setup rides cluster state (PG placement, actor
            # creation): capacity lost to a preempted/killed node must
            # surface as a retryable training failure so the trainer's
            # failure policy gang-restarts, not as a raw crash of fit()
            raise TrainingFailedError(
                f"gang setup failed: {type(e).__name__}: {e}") from e

    def _setup_backend(self):
        wg = self.worker_group
        n = wg.num_workers
        # framework backends (TorchConfig etc.) own their rendezvous
        if self.backend_config is not None and hasattr(
                self.backend_config, "setup_worker_group"):
            self.backend_config.setup_worker_group(wg)
            return
        if n > 1:
            # coordinator on rank 0's host (reference: rank-0 TCP rendezvous,
            # train/torch/config.py:113 — here it's jax.distributed's
            # coordination service over DCN)
            ip = wg.execute_single(0, "get_ip")
            port = wg.execute_single(0, "get_free_port")
            coordinator = f"{ip}:{port}"
            import ray_tpu
            refs = [w.setup_jax_distributed.remote(coordinator, n, rank)
                    for rank, w in enumerate(wg.workers)]
            ray_tpu.get(refs, timeout=300)

    def start_training(self, train_func: Callable, config: Dict[str, Any],
                       checkpoint=None, dataset_shards: Optional[Dict] = None,
                       trial_info: Optional[Dict[str, str]] = None,
                       checkpoint_root: Optional[str] = None,
                       ckpt_start_step: int = 0):
        wg = self.worker_group
        n = wg.num_workers
        # node/local ranks from sorted metadata
        node_ids = [m["node_id"] for m in wg.metadata]
        node_rank_map: Dict[str, int] = {}
        for nid in node_ids:
            if nid not in node_rank_map:
                node_rank_map[nid] = len(node_rank_map)
        local_counter: Dict[str, int] = defaultdict(int)
        trial_info = trial_info or {}
        import ray_tpu
        refs = []
        for rank, w in enumerate(wg.workers):
            nid = node_ids[rank]
            refs.append(w.setup_session.remote(
                world_rank=rank, local_rank=local_counter[nid],
                node_rank=node_rank_map[nid], world_size=n,
                checkpoint=checkpoint,
                trial_name=trial_info.get("trial_name", ""),
                trial_id=trial_info.get("trial_id", ""),
                experiment_name=trial_info.get("experiment_name", ""),
                checkpoint_root=checkpoint_root,
                ckpt_start_step=ckpt_start_step))
            local_counter[nid] += 1
        ray_tpu.get(refs, timeout=120)
        if dataset_shards:
            refs = []
            for name, shards in dataset_shards.items():
                for rank, w in enumerate(wg.workers):
                    shard = shards[rank] if isinstance(shards, list) \
                        else shards
                    refs.append(w.set_dataset_shard.remote(name, shard))
            ray_tpu.get(refs, timeout=120)
        wg.execute("start_training", train_func, config, timeout=120)

    def get_next_results(self, timeout: float = 600.0
                         ) -> Optional[List[TrainingResult]]:
        """One result round: every worker reports once, or all finish.

        Returns None when training completed on all workers; raises on any
        worker error (gang semantics: one failure fails the round, matching
        ICI gang-fatality)."""
        import time
        import ray_tpu
        wg = self.worker_group
        deadline = time.monotonic() + timeout
        results: List[Optional[Dict]] = [None] * wg.num_workers
        while time.monotonic() < deadline:
            pending = [i for i, r in enumerate(results) if r is None]
            if not pending:
                return [TrainingResult(r["metrics"], r.get("checkpoint"))
                        for r in results]
            finished = 0
            for i in pending:
                try:
                    r = ray_tpu.get(
                        wg.workers[i].get_next_result.remote(2.0),
                        timeout=60)
                except Exception as e:
                    # a dead gang member (preempted node, killed actor)
                    # fails the round retryably — gang semantics, same
                    # as a worker-reported error
                    raise TrainingFailedError(
                        f"worker {i} unreachable: "
                        f"{type(e).__name__}: {e}") from e
                if r["status"] == "result":
                    results[i] = r
                elif r["status"] == "error":
                    raise TrainingFailedError(
                        f"worker {i} failed:\n{r['error']}")
                elif r["status"] == "finished":
                    finished += 1
            if finished == len(pending) and all(
                    r is None for r in results):
                return None
            if finished == len(pending) and any(
                    r is not None for r in results):
                # stragglers finished without reporting this round
                return [TrainingResult(r["metrics"], r.get("checkpoint"))
                        if r else TrainingResult({}) for r in results]
        raise TrainingFailedError("timed out waiting for worker results")

    def wait_for_checkpoints(self, timeout: float = 300.0) -> List[Any]:
        """Barrier over every rank's in-flight async checkpoint write —
        the precondition for the driver committing a step."""
        return self.worker_group.execute("wait_checkpoint", timeout=timeout)

    def finish(self) -> List[Any]:
        return self.worker_group.execute("get_error")

    def shutdown(self):
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
