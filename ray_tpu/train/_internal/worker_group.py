"""Gang worker group for Train.

Reference analogue: train/_internal/worker_group.py:92 — a set of actors
forming one training gang, placed in a placement group so the whole gang
schedules atomically (on TPU: a gang == an SPMD island; one worker per host
of the slice; slice atomicity per SURVEY.md §7 'Gang semantics')."""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air import session as air_session
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class TrainWorker:
    """One gang member. Runs the user's train_func in a thread and streams
    session results (reference: backend_executor start_training / session)."""

    def __init__(self):
        self._session = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[str] = None
        self._finished = False
        self._return_value = None

    def get_metadata(self) -> Dict[str, Any]:
        import os
        ctx = ray_tpu.get_runtime_context()
        return {
            "node_id": ctx.get_node_id(),
            "pid": os.getpid(),
            "hostname": os.uname().nodename,
            "tpu_chips": ray_tpu.get_tpu_ids(),
        }

    def setup_session(self, *, world_rank: int, local_rank: int,
                      node_rank: int, world_size: int,
                      trial_name: str = "", trial_id: str = "",
                      experiment_name: str = "",
                      checkpoint=None,
                      checkpoint_root: str = None,
                      ckpt_start_step: int = 0) -> bool:
        ckpt_manager = None
        if checkpoint_root:
            # workers only stage into the root; commit/retention is the
            # driver's job (it owns the all-ranks round barrier)
            from ray_tpu.checkpoint import CheckpointManager
            ckpt_manager = CheckpointManager(checkpoint_root)
        self._session = air_session._Session(
            world_rank=world_rank, local_rank=local_rank,
            node_rank=node_rank, world_size=world_size,
            trial_name=trial_name, trial_id=trial_id,
            experiment_name=experiment_name, checkpoint=checkpoint,
            tpu_chips=tuple(ray_tpu.get_tpu_ids()),
            checkpoint_manager=ckpt_manager,
            ckpt_next_step=ckpt_start_step)
        return True

    def wait_checkpoint(self):
        """Barrier until this worker's in-flight async checkpoint write
        (if any) has landed; returns its per-save stats. The driver calls
        this on every rank before committing a step."""
        s = self._session
        if s is None or s.async_checkpointer is None:
            return []
        s.async_checkpointer.wait()
        return [st.as_dict() for st in s.async_checkpointer.stats]

    def set_dataset_shard(self, name: str, shard) -> bool:
        self._session.dataset_shards[name] = shard
        return True

    def setup_jax_distributed(self, coordinator: str, num_processes: int,
                              process_id: int) -> bool:
        """Join the SPMD island (replaces torch dist.init_process_group,
        reference train/torch/config.py:69)."""
        if num_processes <= 1:
            return True
        import jax
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return True

    def setup_torch_distributed(self, init_method: str,
                                world_size: int, rank: int,
                                backend: str = "gloo",
                                timeout_s: float = 120.0) -> bool:
        """torch.distributed process group over our actor gang
        (reference: train/torch/config.py:69 _setup_torch_process_group
        — gloo on CPU hosts; NCCL has no TPU meaning)."""
        import datetime
        import torch.distributed as dist
        dist.init_process_group(
            backend=backend, init_method=init_method,
            world_size=world_size, rank=rank,
            timeout=datetime.timedelta(seconds=timeout_s))
        import os
        os.environ["WORLD_SIZE"] = str(world_size)
        os.environ["RANK"] = str(rank)
        return True

    def shutdown_torch_distributed(self) -> bool:
        try:
            import torch.distributed as dist
            if dist.is_initialized():
                dist.destroy_process_group()
        except Exception:
            pass
        return True

    def get_free_port(self) -> int:
        import socket
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def get_ip(self) -> str:
        import socket
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.connect(("8.8.8.8", 80))
            ip = s.getsockname()[0]
            s.close()
            return ip
        except Exception:
            return "127.0.0.1"

    def start_training(self, train_func: Callable, config: Dict[str, Any]
                       ) -> bool:
        air_session._set_session(self._session)

        def run():
            air_session._set_session(self._session)
            try:
                import inspect
                sig = inspect.signature(train_func)
                if len(sig.parameters) >= 1:
                    self._return_value = train_func(config)
                else:
                    self._return_value = train_func()
            except StopIteration:
                pass
            except BaseException:  # noqa: BLE001
                self._error = traceback.format_exc()
            finally:
                self._finished = True

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="train_func")
        self._thread.start()
        return True

    def get_next_result(self, timeout: float = 5.0):
        """Long-poll one TrainingResult; returns dict or status marker."""
        import queue as _q
        if self._session is None:
            return {"status": "no_session"}
        try:
            r = self._session.result_queue.get(timeout=timeout)
            return {"status": "result", "metrics": r.metrics,
                    "checkpoint": r.checkpoint}
        except _q.Empty:
            if self._error is not None:
                return {"status": "error", "error": self._error}
            if self._finished:
                return {"status": "finished",
                        "return_value": self._return_value}
            return {"status": "pending"}

    def is_finished(self) -> bool:
        return self._finished

    def get_error(self) -> Optional[str]:
        return self._error

    def shutdown_jax(self) -> bool:
        try:
            import jax
            jax.distributed.shutdown()
        except Exception:
            pass
        return True


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK",
                 tpu_topology: Optional[str] = None):
        self.num_workers = num_workers
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        self.pg = placement_group(bundles, strategy=placement_strategy)
        if not self.pg.ready(timeout=120):
            remove_placement_group(self.pg)
            raise RuntimeError(
                f"placement group for {num_workers} x "
                f"{resources_per_worker} did not become ready")
        opts: Dict[str, Any] = {}
        num_tpus = resources_per_worker.get("TPU", 0)
        num_cpus = resources_per_worker.get("CPU", 1)
        self.workers = []
        for i in range(num_workers):
            w = TrainWorker.options(
                num_cpus=num_cpus, num_tpus=num_tpus,
                tpu_topology=tpu_topology,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    self.pg, placement_group_bundle_index=i),
            ).remote()
            self.workers.append(w)
        # stable rank ordering: sort by (node, pid) like the reference's
        # rank mapping (backend_executor.py:380)
        metas = ray_tpu.get([w.get_metadata.remote() for w in self.workers],
                            timeout=180)
        order = sorted(range(num_workers),
                       key=lambda i: (metas[i]["node_id"], metas[i]["pid"]))
        self.workers = [self.workers[i] for i in order]
        self.metadata = [metas[i] for i in order]

    def execute(self, method_name: str, *args, timeout=180, **kwargs):
        refs = [getattr(w, method_name).remote(*args, **kwargs)
                for w in self.workers]
        return ray_tpu.get(refs, timeout=timeout)

    def execute_single(self, rank: int, method_name: str, *args,
                       timeout=180, **kwargs):
        ref = getattr(self.workers[rank], method_name).remote(*args, **kwargs)
        return ray_tpu.get(ref, timeout=timeout)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
