"""TorchTrainer — torch training on the actor gang (CPU/gloo).

Reference analogue: train/torch/ (TorchTrainer, TorchConfig
config.py:69, prepare_model/prepare_data_loader
train_loop_utils.py:51). On TPU clusters torch runs host-side (gloo) —
the TPU compute path is the JAX backend; this trainer exists for
capability parity with torch-based data/eval pipelines.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.train.data_parallel_trainer import (BaseTrainer,
                                                 DataParallelTrainer)


class TorchConfig:
    """Backend config forming a torch.distributed gloo group."""

    def __init__(self, backend: str = "gloo",
                 timeout_s: float = 120.0):
        self.backend = backend
        self.timeout_s = timeout_s

    def setup_worker_group(self, worker_group):
        n = worker_group.num_workers
        if n <= 1:
            return
        ip = worker_group.execute_single(0, "get_ip")
        port = worker_group.execute_single(0, "get_free_port")
        init_method = f"tcp://{ip}:{port}"
        refs = [w.setup_torch_distributed.remote(
                    init_method, n, rank, self.backend, self.timeout_s)
                for rank, w in enumerate(worker_group.workers)]
        ray_tpu.get(refs, timeout=300)


class TorchTrainer(DataParallelTrainer):
    def __init__(self, train_loop_per_worker: Callable,
                 *, torch_config: Optional[TorchConfig] = None,
                 **kwargs):
        kwargs.setdefault("backend_config",
                          torch_config or TorchConfig())
        super().__init__(train_loop_per_worker, **kwargs)


def prepare_model(model):
    """Wrap in DDP when a process group exists (reference:
    train_loop_utils.py:51)."""
    import torch.distributed as dist
    if dist.is_available() and dist.is_initialized() and \
            dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Re-wrap a DataLoader with a DistributedSampler shard."""
    import torch.distributed as dist
    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=DistributedSampler(data_loader.dataset),
        num_workers=0,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last)
