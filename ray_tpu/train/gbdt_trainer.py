"""GBDT trainers: XGBoost / LightGBM / sklearn gradient boosting.

Reference analogue: python/ray/train/gbdt_trainer.py (the shared
XGBoost/LightGBM trainer riding xgboost-ray/lightgbm-ray) plus
train/sklearn/sklearn_trainer.py. The shape is the reference's: a
trainer that materializes its Ray Datasets into matrices inside a
framework-managed worker, fits the booster, reports eval metrics
through the session, and checkpoints the fitted model. xgboost and
lightgbm are not baked into this image, so those subclasses gate on
import exactly like the reference does when its integrations are
missing; the sklearn backend is fully functional and exercises the
entire shared path.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import (BaseTrainer,
                                                 DataParallelTrainer,
                                                 Result)

MODEL_KEY = "model.pkl"


def _dataset_to_xy(ds, label_column: str):
    """Materialize a Dataset (or plain dict/arrays) into X, y."""
    if hasattr(ds, "take_all"):
        rows = ds.take_all()
        if rows and isinstance(rows[0], dict):
            ys = np.asarray([r[label_column] for r in rows])
            feat_keys = [k for k in rows[0] if k != label_column]
            xs = np.asarray([[r[k] for k in feat_keys] for r in rows])
            return xs, ys
        arr = np.asarray(rows)
        return arr[:, :-1], arr[:, -1]
    if isinstance(ds, dict):
        return np.asarray(ds["X"]), np.asarray(ds["y"])
    raise TypeError(f"cannot turn {type(ds)} into a matrix")


class GBDTTrainer(BaseTrainer):
    """Shared GBDT orchestration (reference: gbdt_trainer.py): the fit
    runs in ONE framework-managed worker (boosting is not data-parallel
    here — the reference distributes via xgboost-ray's rabit ring; this
    image has no xgboost at all, so the gang stays size-1 and the seam
    is the `_fit_model` hook)."""

    _framework = "gbdt"

    def __init__(self, *, label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 num_boost_round: int = 50,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.label_column = label_column
        self.params = params or {}
        self.num_boost_round = num_boost_round

    # subclasses override: fit + eval, return (model, metrics)
    def _fit_model(self, X, y, eval_sets, config):
        raise NotImplementedError

    def _with_config_overrides(self, config: Dict[str, Any]):
        merged = {**self.params, **(config or {})}
        clone = type(self)(
            label_column=self.label_column, params=merged,
            num_boost_round=self.num_boost_round,
            scaling_config=self.scaling_config,
            run_config=self.run_config, datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)
        return clone

    def fit(self) -> Result:
        return self._fit_internal(report_through_session=False)

    def _fit_internal(self, report_through_session: bool) -> Result:
        trainer = self

        def train_loop(config):
            from ray_tpu.air import session
            train_ds = session.get_dataset_shard("train")
            X, y = _dataset_to_xy(
                train_ds if train_ds is not None
                else trainer.datasets["train"], trainer.label_column)
            eval_sets = {}
            for name, ds in trainer.datasets.items():
                if name != "train":
                    eval_sets[name] = _dataset_to_xy(
                        ds, trainer.label_column)
            model, metrics = trainer._fit_model(X, y, eval_sets, config)
            ckpt = Checkpoint.from_dict(
                {MODEL_KEY: pickle.dumps(model),
                 "label_column": trainer.label_column})
            session.report(metrics, checkpoint=ckpt)

        inner = DataParallelTrainer(
            train_loop, train_loop_config=dict(self.params),
            scaling_config=self.scaling_config,
            run_config=self.run_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)
        return inner._fit_internal(report_through_session)

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        d = checkpoint.to_dict()
        return pickle.loads(d[MODEL_KEY])


class SklearnGBDTTrainer(GBDTTrainer):
    """Gradient boosting via scikit-learn (fully functional in this
    image; reference analogue: train/sklearn/sklearn_trainer.py)."""

    _framework = "sklearn"

    def _fit_model(self, X, y, eval_sets, config):
        from sklearn.ensemble import (GradientBoostingClassifier,
                                      GradientBoostingRegressor)
        params = dict(config or {})
        objective = params.pop("objective", "classification")
        params.setdefault("n_estimators", self.num_boost_round)
        cls = (GradientBoostingRegressor if objective == "regression"
               else GradientBoostingClassifier)
        model = cls(**params)
        model.fit(X, y)
        metrics: Dict[str, Any] = {
            "train-score": float(model.score(X, y))}
        for name, (Xe, ye) in eval_sets.items():
            metrics[f"{name}-score"] = float(model.score(Xe, ye))
        return model, metrics


class XGBoostTrainer(GBDTTrainer):
    """XGBoost trainer (reference: train/xgboost/xgboost_trainer.py).
    Gated on the xgboost package, which this image does not bake."""

    _framework = "xgboost"

    def _fit_model(self, X, y, eval_sets, config):
        try:
            import xgboost as xgb
        except ImportError as e:
            raise ImportError(
                "XGBoostTrainer requires xgboost: pip install xgboost"
            ) from e
        dtrain = xgb.DMatrix(X, label=y)
        evals = [(xgb.DMatrix(Xe, label=ye), name)
                 for name, (Xe, ye) in eval_sets.items()]
        evals_result: Dict[str, Any] = {}
        model = xgb.train(dict(config or {}), dtrain,
                          num_boost_round=self.num_boost_round,
                          evals=evals, evals_result=evals_result)
        metrics = {f"{name}-{m}": vals[-1]
                   for name, per in evals_result.items()
                   for m, vals in per.items()}
        return model, metrics


class LightGBMTrainer(GBDTTrainer):
    """LightGBM trainer (reference: train/lightgbm/lightgbm_trainer.py).
    Gated on the lightgbm package, which this image does not bake."""

    _framework = "lightgbm"

    def _fit_model(self, X, y, eval_sets, config):
        try:
            import lightgbm as lgb
        except ImportError as e:
            raise ImportError(
                "LightGBMTrainer requires lightgbm: "
                "pip install lightgbm") from e
        train_set = lgb.Dataset(X, label=y)
        valid = [lgb.Dataset(Xe, label=ye)
                 for _, (Xe, ye) in eval_sets.items()]
        model = lgb.train(dict(config or {}), train_set,
                          num_boost_round=self.num_boost_round,
                          valid_sets=valid)
        return model, {"train-best-iter": model.best_iteration or 0}
