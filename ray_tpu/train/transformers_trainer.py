"""TransformersTrainer — Hugging Face transformers on the worker gang.

Reference analogue: train/huggingface/huggingface_trainer.py
(HuggingFaceTrainer:86): the user supplies ``trainer_init_per_worker``
building a ``transformers.Trainer``; each gang worker joins the torch
process group (gloo host-side, as in TorchTrainer) so HF's own
distributed handling shards the data and all-reduces gradients. EVERY
rank reports per logging step (the gang's result rounds complete only
when all workers report), rank 0 attaching portable checkpoints; gang
restarts resume from the last reported checkpoint.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.torch_trainer import TorchTrainer


def _checkpoint_from_hf_dir(ckpt_dir: str):
    """Portable dict-checkpoint from a (flat) HF checkpoint directory —
    a path-only checkpoint is useless off the node that wrote it."""
    from ray_tpu.air.checkpoint import Checkpoint
    data: Dict[str, Any] = {}
    for name in sorted(os.listdir(ckpt_dir)):
        p = os.path.join(ckpt_dir, name)
        if os.path.isfile(p):
            with open(p, "rb") as f:
                data[name] = f.read()
    return Checkpoint.from_dict(data)


def _hf_dir_from_checkpoint(ckpt) -> Optional[str]:
    import tempfile
    data = ckpt.to_dict()
    files = {k: v for k, v in data.items() if isinstance(v, bytes)}
    if not files:
        return None
    d = tempfile.mkdtemp(prefix="hf_resume_")
    for name, blob in files.items():
        with open(os.path.join(d, name), "wb") as f:
            f.write(blob)
    return d


def _make_train_loop(trainer_init_per_worker: Callable):
    def _loop(config: Dict[str, Any]):
        import transformers

        from ray_tpu.air import session

        # HF/accelerate reads the process group from env vars; the gang
        # joined via an explicit tcp:// init_method (TorchConfig), so
        # mirror it into the env form accelerate expects
        try:
            import torch.distributed as dist
            if dist.is_available() and dist.is_initialized():
                os.environ.setdefault("RANK", str(dist.get_rank()))
                os.environ.setdefault("WORLD_SIZE",
                                      str(dist.get_world_size()))
                os.environ.setdefault("LOCAL_RANK",
                                      str(session.get_local_rank()))
                os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
                os.environ.setdefault("MASTER_PORT", "29500")
        except ImportError:
            pass

        class _ReportCallback(transformers.TrainerCallback):
            """EVERY rank reports each on_log/on_save so the executor's
            per-round barrier (backend_executor.get_next_results) always
            completes; rank 0 carries the real metrics/checkpoint."""

            def on_log(self, args, state, control, logs=None, **kw):
                metrics = {k: v for k, v in (logs or {}).items()
                           if isinstance(v, (int, float))}
                metrics["step"] = state.global_step
                metrics["epoch"] = float(state.epoch or 0.0)
                session.report(metrics)

            def on_save(self, args, state, control, **kw):
                ckpt = None
                if state.is_world_process_zero:
                    ckpt_dir = \
                        transformers.trainer_utils.get_last_checkpoint(
                            args.output_dir)
                    if ckpt_dir:
                        ckpt = _checkpoint_from_hf_dir(ckpt_dir)
                session.report({"step": state.global_step,
                                "_checkpoint_saved": ckpt is not None},
                               checkpoint=ckpt)

        # shard keys routed through the Dataset pipeline
        for key, cfg_key in (("train", "_train_dataset"),
                             ("evaluation", "_eval_dataset")):
            if config.pop(f"_shard_{key}", False):
                config[cfg_key] = _shard_to_torch_dataset(
                    session.get_dataset_shard(key))

        trainer = trainer_init_per_worker(
            train_dataset=config.pop("_train_dataset", None),
            eval_dataset=config.pop("_eval_dataset", None),
            **config)
        if not isinstance(trainer, transformers.Trainer):
            raise TypeError(
                "trainer_init_per_worker must return a "
                f"transformers.Trainer, got {type(trainer).__name__}")
        trainer.add_callback(_ReportCallback())
        # gang restart: resume from the checkpoint the session carries
        resume_dir = None
        prev = session.get_checkpoint()
        if prev is not None:
            resume_dir = _hf_dir_from_checkpoint(prev)
        result = trainer.train(resume_from_checkpoint=resume_dir)
        metrics = {k: v for k, v in (result.metrics or {}).items()
                   if isinstance(v, (int, float))}
        metrics["done_training"] = True
        session.report(metrics)
    return _loop


def _shard_to_torch_dataset(shard):
    """Materialize a ray_tpu.data shard as a torch map-style dataset
    (HF's Trainer wants __len__/__getitem__)."""
    import torch

    rows = shard.take_all()

    class _ShardDataset(torch.utils.data.Dataset):
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    return _ShardDataset()


class TransformersTrainer(TorchTrainer):
    def __init__(self, trainer_init_per_worker: Callable,
                 *, trainer_init_config: Optional[Dict[str, Any]] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 **kwargs):
        loop_config = dict(trainer_init_config or {})
        # ray_tpu Datasets flow through the standard get_dataset_shard
        # path (per-worker shards); anything else ships by value inside
        # the config — 'train'/'evaluation' map onto the trainer_init
        # arguments, other keys pass through as extra kwargs
        ds = dict(datasets or {})
        from ray_tpu.data.dataset import Dataset as _RD
        rds = {}
        for key in list(ds):
            v = ds[key]
            if isinstance(v, _RD):
                rds[key] = ds.pop(key)
                if key in ("train", "evaluation"):
                    loop_config[f"_shard_{key}"] = True
        if "train" in ds:
            loop_config["_train_dataset"] = ds.pop("train")
        if "evaluation" in ds:
            loop_config["_eval_dataset"] = ds.pop("evaluation")
        loop_config.update(ds)  # remaining keys pass through verbatim
        super().__init__(
            _make_train_loop(trainer_init_per_worker),
            train_loop_config=loop_config,
            datasets=rds or None, **kwargs)


# exported alias matching the reference's class name
HuggingFaceTrainer = TransformersTrainer
