"""SPMD training-step builders: full jitted train step over a device mesh.

This is the TPU-native heart of the Train layer. The reference wraps the
user's torch model in DDP/FSDP inside each worker (reference:
train/torch/train_loop_utils.py:51 prepare_model, :91 FSDP) and lets NCCL
sync gradients. Here there is no wrapper: the *whole* train step — forward,
backward, optimizer update — is one XLA program jitted over a
`jax.sharding.Mesh`, with parameter/optimizer/data shardings derived from a
`MeshSpec` (dp/fsdp/tp/sp/...). XLA inserts the psum/all-gather/
reduce-scatter collectives over ICI; there is nothing like a process group
to manage.

Design notes (TPU-first):
  - state is a plain dict pytree {params, opt, step}: optax state mirrors
    the param tree, so one path-based sharding rule covers both.
  - `donate_argnums=(0,)` donates the state buffers — the update is
    in-place in HBM, no 2x parameter memory.
  - batch sharding: batch dim over (dp, fsdp), sequence dim over sp (ring
    attention consumes the seq shards).
  - loss/metrics come back replicated (XLA psums them across dp).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.mesh import (MeshSpec, param_sharding)


def state_shardings(abstract_state, mesh, spec: MeshSpec, override=None):
    """Sharding pytree for an arbitrary train-state pytree.

    Optax states (mu/nu of adam) mirror the param tree, so the trailing path
    keys hit the same `param_sharding` rules as the params themselves;
    scalars (step counts, schedules) replicate. `override(keys, shape)` may
    return a NamedSharding to take precedence for special leaves (e.g.
    stage-stacked pipeline params, expert-stacked MoE params).
    """
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    leaves, treedef = tree_flatten_with_path(abstract_state)
    out = []
    for path, leaf in leaves:
        keys = tuple(getattr(p, "key", getattr(p, "idx", str(p)))
                     for p in path)
        shape = getattr(leaf, "shape", ())
        special = override(keys, shape) if override is not None else None
        if special is not None:
            out.append(special)
        elif len(shape) == 0:
            out.append(NamedSharding(mesh, P()))
        else:
            out.append(param_sharding(mesh, keys, shape, spec))
    return tree_unflatten(treedef, out)


@dataclasses.dataclass
class SpmdTrainer:
    """A compiled SPMD training program bound to a mesh.

    init(rng) -> state                (sharded across the mesh)
    step(state, batch) -> state, metrics
    eval_loss(state, batch) -> loss   (optional; pipelined trainers attach a
                                       sequential pp=1 oracle here for
                                       parity checks)
    """
    mesh: Any
    spec: MeshSpec
    init: Callable
    step: Callable
    batch_shardings: Any
    state_sharding_tree: Any
    eval_loss: Optional[Callable] = None


def make_causal_lm_trainer(
    model_config=None,
    *,
    mesh=None,
    spec: Optional[MeshSpec] = None,
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
    model=None,
) -> SpmdTrainer:
    """GPT-style causal-LM SPMD trainer (the flagship train step).

    Reference analogue (capability, not design): the HF GPT-2 fine-tune
    config (train/huggingface/huggingface_trainer.py:157) — there, torch
    Trainer + DDP inside Ray workers; here, one pjit'd program over the mesh.
    """
    from ray_tpu.models.gpt2 import GPT2, GPT2Config, causal_lm_loss

    if spec is None:
        spec = MeshSpec()
    if mesh is None:
        mesh = spec.build()
    if model is None:
        model = GPT2(model_config or GPT2Config.small())

    tx = optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=0.9, b2=0.95,
                    weight_decay=weight_decay),
    )

    seq_probe = 8  # init only traces shapes; seq length is free at step time

    def init_fn(rng):
        params = model.init(rng, jnp.zeros((1, seq_probe), jnp.int32))[
            "params"]
        return {"params": params, "opt": tx.init(params),
                "step": jnp.zeros((), jnp.int32)}

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    st_sh = state_shardings(abstract, mesh, spec)

    init = jax.jit(init_fn, out_shardings=st_sh)

    batch_sh = {
        "input_ids": NamedSharding(mesh, P(("dp", "fsdp"), "sp")),
        "labels": NamedSharding(mesh, P(("dp", "fsdp"), "sp")),
    }
    repl = NamedSharding(mesh, P())

    dropout = float(getattr(model.config, "dropout", 0.0) or 0.0)
    base_rng = jax.random.PRNGKey(17)

    def train_step(state, batch):
        def loss_fn(p):
            if dropout > 0.0:
                logits = model.apply(
                    {"params": p}, batch["input_ids"], deterministic=False,
                    rngs={"dropout": jax.random.fold_in(
                        base_rng, state["step"])})
            else:
                logits = model.apply({"params": p}, batch["input_ids"],
                                     deterministic=True)
            return causal_lm_loss(logits, batch["labels"])

        loss, grads = jax.value_and_grad(loss_fn)(state["params"])
        updates, opt = tx.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        gnorm = optax.global_norm(grads)
        new_state = {"params": params, "opt": opt,
                     "step": state["step"] + 1}
        return new_state, {"loss": loss, "grad_norm": gnorm}

    step = jax.jit(
        train_step,
        in_shardings=(st_sh, batch_sh),
        out_shardings=(st_sh, {"loss": repl, "grad_norm": repl}),
        donate_argnums=(0,),
    )
    return SpmdTrainer(mesh=mesh, spec=spec, init=init, step=step,
                       batch_shardings=batch_sh, state_sharding_tree=st_sh)


def make_image_classifier_trainer(
    model,
    *,
    mesh=None,
    spec: Optional[MeshSpec] = None,
    learning_rate: float = 0.1,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    input_shape: Tuple[int, ...] = (1, 224, 224, 3),
) -> SpmdTrainer:
    """ResNet-style SPMD trainer with batch-norm state.

    Reference analogue: resnet50_ray_air.py (MLPerf-style ResNet-50 DDP
    benchmark). State carries flax `batch_stats`; cross-dp batchnorm uses
    the local shard statistics (the standard large-batch approximation —
    the reference's torch DDP BatchNorm does the same).
    """
    if spec is None:
        spec = MeshSpec()
    if mesh is None:
        mesh = spec.build()

    tx = optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.sgd(learning_rate, momentum=momentum, nesterov=True),
    )

    def init_fn(rng):
        variables = model.init(rng, jnp.zeros(input_shape, jnp.float32),
                               train=False)
        params = variables["params"]
        return {"params": params,
                "batch_stats": variables.get("batch_stats", {}),
                "opt": tx.init(params),
                "step": jnp.zeros((), jnp.int32)}

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    st_sh = state_shardings(abstract, mesh, spec)
    init = jax.jit(init_fn, out_shardings=st_sh)

    batch_sh = {
        "image": NamedSharding(mesh, P(("dp", "fsdp"))),
        "label": NamedSharding(mesh, P(("dp", "fsdp"))),
    }
    repl = NamedSharding(mesh, P())

    def train_step(state, batch):
        img = batch["image"]
        if img.dtype == jnp.uint8:
            # uint8 input pipeline (MLPerf-style): ship bytes, normalize
            # on device — 4x less host->HBM traffic than f32 images
            img = img.astype(jnp.float32) / 127.5 - 1.0

        def loss_fn(p):
            out, mut = model.apply(
                {"params": p, "batch_stats": state["batch_stats"]},
                img, train=True, mutable=["batch_stats"])
            onehot = jax.nn.one_hot(batch["label"], out.shape[-1])
            loss = optax.softmax_cross_entropy(out, onehot).mean()
            return loss, (out, mut["batch_stats"])

        (loss, (logits, new_bs)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"])
        updates, opt = tx.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
        new_state = {"params": params, "batch_stats": new_bs,
                     "opt": opt, "step": state["step"] + 1}
        return new_state, {"loss": loss, "accuracy": acc}

    step = jax.jit(
        train_step,
        in_shardings=(st_sh, batch_sh),
        out_shardings=(st_sh, {"loss": repl, "accuracy": repl}),
        donate_argnums=(0,),
    )
    return SpmdTrainer(mesh=mesh, spec=spec, init=init, step=step,
                       batch_shardings=batch_sh, state_sharding_tree=st_sh)


def make_pipelined_lm_trainer(
    model_config,
    *,
    mesh,
    spec: MeshSpec,
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
) -> SpmdTrainer:
    """Causal-LM trainer with PIPELINE parallelism over the ``pp`` axis.

    Structure: embed (computed outside the pipeline, replicated over pp) →
    stage-stacked transformer Blocks through the microbatched circular
    pipeline (parallel/pipeline.py: shard_map manual over pp, ppermute
    rotation, autodiff backward) → final-LN + untied head. dp shards the
    per-microbatch batch dim and tp/fsdp shard stage weights as usual —
    partial-manual shard_map leaves those axes to GSPMD.

    No reference analogue (the reference has no pipeline engine; SURVEY.md
    §2.6) — this is the TPU-native bar for PP.
    """
    import flax.linen as nn

    from ray_tpu.models.gpt2 import Block, causal_lm_loss
    from ray_tpu.parallel.pipeline import pipeline_apply

    cfg = model_config
    n_stages = spec.pp
    assert cfg.n_layer % n_stages == 0, \
        f"n_layer={cfg.n_layer} must divide into pp={n_stages} stages"
    layers_per_stage = cfg.n_layer // n_stages

    class Embed(nn.Module):
        @nn.compact
        def __call__(self, ids):
            pos = jnp.arange(ids.shape[-1])
            wte = nn.Embed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype,
                           name="wte")
            wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype,
                           name="wpe")
            return wte(ids) + wpe(pos)

    class Stage(nn.Module):
        @nn.compact
        def __call__(self, x):
            for i in range(layers_per_stage):
                x = Block(cfg, name=f"h_{i}")(x, deterministic=True)
            return x

    class Head(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
            return nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                            name="lm_head")(x)

    embed_m, stage_m, head_m = Embed(), Stage(), Head()
    tx = optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=0.9, b2=0.95,
                    weight_decay=weight_decay),
    )

    seq_probe = 8

    def init_fn(rng):
        r_e, r_s, r_h = jax.random.split(rng, 3)
        ids = jnp.zeros((1, seq_probe), jnp.int32)
        x = jnp.zeros((1, seq_probe, cfg.n_embd), cfg.dtype)
        stage_rngs = jax.random.split(r_s, n_stages)
        params = {
            "embed": embed_m.init(r_e, ids)["params"],
            "stages": jax.vmap(
                lambda r: stage_m.init(r, x)["params"])(stage_rngs),
            "head": head_m.init(r_h, x)["params"],
        }
        return {"params": params, "opt": tx.init(params),
                "step": jnp.zeros((), jnp.int32)}

    # shardings: stage-stacked leaves get P("pp", <usual tp/fsdp rule>);
    # embed/head replicate over pp (their tp/fsdp rules still apply)
    def _stage_override(keys, shape):
        if "stages" in keys and len(shape) >= 1:
            inner = param_sharding(mesh, keys, shape[1:], spec)
            return NamedSharding(mesh, P("pp", *inner.spec))
        return None

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    st_sh = state_shardings(abstract, mesh, spec, override=_stage_override)
    init = jax.jit(init_fn, out_shardings=st_sh)

    # batches arrive pre-microbatched: [M, mb, T]; dp shards mb, sp shards T
    batch_sh = {
        "input_ids": NamedSharding(mesh, P(None, ("dp", "fsdp"), "sp")),
        "labels": NamedSharding(mesh, P(None, ("dp", "fsdp"), "sp")),
    }
    repl = NamedSharding(mesh, P())
    piped = pipeline_apply(
        lambda p, x: stage_m.apply({"params": p}, x), mesh)

    def train_step(state, batch):
        def loss_fn(p):
            x = embed_m.apply({"params": p["embed"]}, batch["input_ids"])
            y = piped(p["stages"], x.astype(cfg.dtype))
            logits = head_m.apply({"params": p["head"]}, y)
            return causal_lm_loss(
                logits.reshape(-1, logits.shape[-2], logits.shape[-1]),
                batch["labels"].reshape(-1, batch["labels"].shape[-1]))

        loss, grads = jax.value_and_grad(loss_fn)(state["params"])
        updates, opt = tx.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt": opt,
                     "step": state["step"] + 1}
        return new_state, {"loss": loss,
                           "grad_norm": optax.global_norm(grads)}

    step = jax.jit(
        train_step,
        in_shardings=(st_sh, batch_sh),
        out_shardings=(st_sh, {"loss": repl, "grad_norm": repl}),
        donate_argnums=(0,),
    )

    def eval_loss_fn(state, batch):
        """pp=1 oracle: the same params through sequential_apply."""
        from ray_tpu.parallel.pipeline import sequential_apply
        p = state["params"]
        x = embed_m.apply({"params": p["embed"]}, batch["input_ids"])
        y = sequential_apply(
            lambda sp, xx: stage_m.apply({"params": sp}, xx),
            p["stages"], x.astype(cfg.dtype))
        logits = head_m.apply({"params": p["head"]}, y)
        return causal_lm_loss(
            logits.reshape(-1, logits.shape[-2], logits.shape[-1]),
            batch["labels"].reshape(-1, batch["labels"].shape[-1]))

    return SpmdTrainer(mesh=mesh, spec=spec, init=init, step=step,
                       batch_shardings=batch_sh, state_sharding_tree=st_sh,
                       eval_loss=jax.jit(eval_loss_fn))


def make_async_checkpointer(manager=None, **kwargs):
    """An AsyncCheckpointer for SPMD train state. With ``manager=None``
    inside a train session, binds to the run's durable checkpoint root
    (the driver commits after the gang round barrier); standalone callers
    pass their own CheckpointManager and get self-committing saves.

    Usage in a train_func::

        ckpter = spmd.make_async_checkpointer()
        ...
        pending = ckpter.save(session.next_checkpoint_step(), state)
        session.report(metrics, checkpoint=pending)   # blocks only for
        ...                                           # the host snapshot
        ckpter.finalize()                             # before returning
    """
    from ray_tpu.checkpoint import AsyncCheckpointer
    if manager is None:
        from ray_tpu.air import session as air_session
        ckpter = air_session.get_async_checkpointer()
        if ckpter is None:
            raise RuntimeError(
                "no checkpoint manager in the session — set "
                "RunConfig.name/storage_path, or pass manager= explicitly")
        return ckpter
    return AsyncCheckpointer(manager, **kwargs)


def restore_spmd_state(target_state, *, manager=None, checkpoint=None,
                       step: Optional[int] = None):
    """Restore a sharded checkpoint onto ``target_state``'s shardings.

    World-size/mesh independent: shards are keyed by *global* index
    slices, so a state saved by 8 processes on a (dp=4, tp=2) mesh
    reassembles onto 1 process with a (dp=2,) mesh (and vice versa) —
    each leaf is rebuilt full on host and ``device_put`` re-shards it to
    the target layout. Source: a CheckpointManager (committed step, with
    checksum verification under RTPU_CKPT_VERIFY=1), a directory-backed
    air.Checkpoint, or the session's manager."""
    from ray_tpu.air.checkpoint import ShardedCheckpoint
    if manager is None and checkpoint is None:
        from ray_tpu.air import session as air_session
        manager = air_session.get_checkpoint_manager()
        if manager is None:
            checkpoint = air_session.get_checkpoint()
    if manager is not None:
        return manager.restore_state(target_state, step=step)
    root = getattr(checkpoint, "_dir", None)
    if root is None:
        raise ValueError("restore_spmd_state needs a CheckpointManager or "
                         "a directory-backed Checkpoint")
    return ShardedCheckpoint(root).restore(target_state)


def put_batch(trainer: SpmdTrainer, batch: Dict[str, np.ndarray]):
    """Host batch -> sharded device arrays matching the trainer layout."""
    return {k: jax.device_put(v, trainer.batch_shardings[k])
            for k, v in batch.items()}


def default_spec_for(n_devices: int) -> MeshSpec:
    """A sensible multi-axis MeshSpec exercising dp/tp/sp for N devices.

    Used by the multichip dryrun: factorize N into (dp, sp, tp) with tp/sp
    innermost (ICI-adjacent), dp taking the remainder.
    """
    tp = 2 if n_devices % 2 == 0 else 1
    rem = n_devices // tp
    sp = 2 if rem % 2 == 0 and rem >= 4 else 1
    dp = rem // sp
    return MeshSpec(dp=dp, sp=sp, tp=tp)
