"""RLTrainer — run an RLlib algorithm through the Train API.

Reference analogue: python/ray/train/rl/rl_trainer.py (+ rl_predictor):
the trainer builds the Algorithm inside a framework-managed worker,
steps it for ``num_iterations``, reports each iteration's metrics
through the session, and checkpoints the algorithm state so
``RLTrainer.get_policy`` can rebuild a serving policy from the AIR
Checkpoint.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional, Type, Union

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import (BaseTrainer,
                                                 DataParallelTrainer, Result)

ALGO_KEY = "rllib_state.pkl"


class RLTrainer(BaseTrainer):
    """Train an RLlib algorithm as a Train workload."""

    _framework = "rl"

    def __init__(self, *, algorithm: Union[str, Type] = "PPO",
                 config: Optional[Dict[str, Any]] = None,
                 num_iterations: int = 3,
                 stop_reward: Optional[float] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.algorithm = algorithm
        self.algo_config = dict(config or {})
        self.num_iterations = num_iterations
        self.stop_reward = stop_reward

    def _with_config_overrides(self, config: Dict[str, Any]):
        merged = {**self.algo_config, **(config or {})}
        return type(self)(
            algorithm=self.algorithm, config=merged,
            num_iterations=self.num_iterations,
            stop_reward=self.stop_reward,
            scaling_config=self.scaling_config, run_config=self.run_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)

    @staticmethod
    def _algo_cls(algorithm):
        if not isinstance(algorithm, str):
            return algorithm
        from ray_tpu.rllib import algorithms
        cls = getattr(algorithms, algorithm, None)
        if cls is None:
            raise ValueError(f"unknown RLlib algorithm {algorithm!r}")
        return cls

    def fit(self) -> Result:
        return self._fit_internal(report_through_session=False)

    def _fit_internal(self, report_through_session: bool) -> Result:
        trainer = self

        def train_loop(config):
            from ray_tpu.air import session
            cls = RLTrainer._algo_cls(trainer.algorithm)
            algo = cls(config=dict(config or {}))
            try:
                last = {}
                for it in range(trainer.num_iterations):
                    last = algo.train()
                    reward = last.get("episode_reward_mean")
                    metrics = {
                        "training_iteration": it + 1,
                        "episode_reward_mean": reward,
                        "episodes_total": last.get("episodes_total"),
                    }
                    done = (trainer.stop_reward is not None
                            and reward is not None
                            and reward >= trainer.stop_reward)
                    if it == trainer.num_iterations - 1 or done:
                        state = algo.save_checkpoint()
                        algo_name = (trainer.algorithm
                                     if isinstance(trainer.algorithm, str)
                                     else trainer.algorithm.__name__)
                        ckpt = Checkpoint.from_dict(
                            {ALGO_KEY: pickle.dumps(state),
                             "algorithm": algo_name,
                             "config": dict(config or {})})
                        session.report(metrics, checkpoint=ckpt)
                        if done:
                            break
                    else:
                        session.report(metrics)
            finally:
                algo.cleanup()

        inner = DataParallelTrainer(
            train_loop, train_loop_config=dict(self.algo_config),
            scaling_config=self.scaling_config, run_config=self.run_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)
        return inner._fit_internal(report_through_session)

    @staticmethod
    def restore_algorithm(checkpoint: Checkpoint):
        """Rebuild the trained Algorithm from an AIR checkpoint."""
        d = checkpoint.to_dict()
        cls = RLTrainer._algo_cls(d["algorithm"])
        algo = cls(config=dict(d.get("config") or {}))
        algo.load_checkpoint(pickle.loads(d[ALGO_KEY]))
        return algo
