"""ray_tpu.train — distributed training orchestration (reference: ray.train)."""

from ray_tpu.train.data_parallel_trainer import (  # noqa: F401
    BaseTrainer, DataParallelTrainer, JaxConfig, Result)
from ray_tpu.train._internal.backend_executor import (  # noqa: F401
    BackendExecutor, TrainingFailedError)
from ray_tpu.air import session  # noqa: F401
from ray_tpu.air.session import (  # noqa: F401
    report, get_checkpoint, get_dataset_shard, get_world_rank,
    get_local_rank, get_node_rank, get_world_size, get_mesh)
from ray_tpu.air.checkpoint import Checkpoint, ShardedCheckpoint  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig, FailureConfig, RunConfig, ScalingConfig)
from ray_tpu.checkpoint import (  # noqa: F401
    AsyncCheckpointer, CheckpointManager, PendingCheckpoint)
from ray_tpu.train.gbdt_trainer import (  # noqa: F401
    GBDTTrainer, LightGBMTrainer, SklearnGBDTTrainer, XGBoostTrainer)
from ray_tpu.train.torch_trainer import (  # noqa: F401
    TorchConfig, TorchTrainer, prepare_data_loader, prepare_model)
from ray_tpu.train.transformers_trainer import (  # noqa: F401
    HuggingFaceTrainer, TransformersTrainer)
from ray_tpu.train.sklearn_trainer import SklearnTrainer  # noqa: F401
from ray_tpu.train.lightning_trainer import LightningTrainer  # noqa: F401
from ray_tpu.train.rl_trainer import RLTrainer  # noqa: F401
