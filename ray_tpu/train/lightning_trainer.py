"""LightningTrainer — run a PyTorch Lightning fit inside the Train
worker gang.

Reference analogue: the `ray_lightning` shim the reference bundles
(SURVEY §2.4: train table, util/ray_lightning) and the later in-tree
``LightningTrainer``.  Lightning is not baked into this image, so the
constructor gates on the import exactly like the reference's optional
integrations; when present, each Train worker builds the module +
``pl.Trainer`` with the environment's rank info (the gloo process group
is already formed by TorchConfig, so Lightning's ddp strategy finds an
initialized backend) and checkpoints the module state dict.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.torch_trainer import TorchConfig, TorchTrainer


def _lightning():
    try:
        import pytorch_lightning as pl
        return pl
    except ImportError:
        try:
            import lightning.pytorch as pl
            return pl
        except ImportError:
            raise ImportError(
                "LightningTrainer requires `pytorch_lightning` (or "
                "`lightning`), which is not installed in this image. "
                "Use TorchTrainer with an explicit loop, or "
                "DataParallelTrainer for the JAX-native path.") from None


class LightningTrainer(TorchTrainer):
    """Gang-run a ``LightningModule.fit`` (gated on lightning)."""

    _framework = "lightning"

    def __init__(self, *, lightning_module_cls=None,
                 module_init_config: Optional[Dict[str, Any]] = None,
                 trainer_init_config: Optional[Dict[str, Any]] = None,
                 datamodule_fn=None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 torch_config: Optional[TorchConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        _lightning()  # gate early, like the reference's soft imports
        self._module_cls = lightning_module_cls
        self._module_cfg = dict(module_init_config or {})
        self._trainer_cfg = dict(trainer_init_config or {})
        self._datamodule_fn = datamodule_fn

        def train_loop(config):
            import torch

            from ray_tpu.air import session
            pl = _lightning()
            module = self._module_cls(**self._module_cfg)
            kw = dict(self._trainer_cfg)
            kw.setdefault("enable_progress_bar", False)
            kw.setdefault("logger", False)
            kw.setdefault("enable_checkpointing", False)
            trainer = pl.Trainer(**kw)
            fit_kw = {}
            if self._datamodule_fn is not None:
                fit_kw["datamodule"] = self._datamodule_fn()
            trainer.fit(module, **fit_kw)
            metrics = {k: float(v) for k, v in
                       trainer.callback_metrics.items()
                       if hasattr(v, "__float__")}
            ckpt = Checkpoint.from_dict(
                {"state_dict": {k: v.cpu().numpy() for k, v in
                                module.state_dict().items()},
                 "torch": True})
            session.report(metrics or {"done": 1.0}, checkpoint=ckpt)

        super().__init__(
            train_loop_per_worker=train_loop,
            scaling_config=scaling_config, run_config=run_config,
            torch_config=torch_config, datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)
