"""SklearnTrainer — fit an arbitrary scikit-learn estimator through the
Train API, with cluster-parallel cross-validation.

Reference analogue: python/ray/train/sklearn/sklearn_trainer.py — one
framework-managed worker fits the estimator (sklearn is not
data-parallel), `cv` folds are scored as separate cluster tasks (the
reference parallelizes them with joblib-on-ray via
``parallelize_cv=True``; here the folds ARE tasks), and the fitted
estimator rides an AIR Checkpoint.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import (BaseTrainer,
                                                 DataParallelTrainer, Result)
from ray_tpu.train.gbdt_trainer import MODEL_KEY, _dataset_to_xy


class SklearnTrainer(BaseTrainer):
    """Fit any sklearn estimator; optionally k-fold cross-validate with
    each fold scored in its own cluster task."""

    _framework = "sklearn"

    def __init__(self, *, estimator=None, label_column: str,
                 params: Optional[Dict[str, Any]] = None,
                 cv: int = 0, parallelize_cv: bool = True,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.estimator = estimator
        self.label_column = label_column
        self.params = params or {}
        self.cv = int(cv)
        self.parallelize_cv = parallelize_cv

    def _with_config_overrides(self, config: Dict[str, Any]):
        merged = {**self.params, **(config or {})}
        return type(self)(
            estimator=self.estimator, label_column=self.label_column,
            params=merged, cv=self.cv,
            parallelize_cv=self.parallelize_cv,
            scaling_config=self.scaling_config, run_config=self.run_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)

    def fit(self) -> Result:
        return self._fit_internal(report_through_session=False)

    def _fit_internal(self, report_through_session: bool) -> Result:
        trainer = self

        def train_loop(config):
            import numpy as np
            from sklearn.base import clone

            import ray_tpu
            from ray_tpu.air import session

            train_ds = session.get_dataset_shard("train")
            X, y = _dataset_to_xy(
                train_ds if train_ds is not None
                else trainer.datasets["train"], trainer.label_column)
            est = clone(trainer.estimator)
            if config:
                est.set_params(**{k: v for k, v in config.items()
                                  if k in est.get_params()})
            metrics: Dict[str, Any] = {}

            if trainer.cv and trainer.cv > 1:
                # k-fold CV: each fold is a cluster task (reference:
                # sklearn_trainer's parallelize_cv via joblib-on-ray)
                from sklearn.model_selection import KFold
                folds = list(KFold(n_splits=trainer.cv, shuffle=True,
                                   random_state=0).split(X))
                est_blob = pickle.dumps(est)

                @ray_tpu.remote
                def _score_fold(blob, X, y, tr_idx, te_idx):
                    m = pickle.loads(blob)
                    m.fit(X[tr_idx], y[tr_idx])
                    return float(m.score(X[te_idx], y[te_idx]))

                if trainer.parallelize_cv:
                    refs = [_score_fold.remote(est_blob, X, y, tr, te)
                            for tr, te in folds]
                    scores = ray_tpu.get(refs)
                else:
                    scores = [ray_tpu.get(
                        _score_fold.remote(est_blob, X, y, tr, te))
                        for tr, te in folds]
                metrics["cv_scores"] = scores
                metrics["cv_score_mean"] = float(np.mean(scores))
                metrics["cv_score_std"] = float(np.std(scores))

            t0 = time.perf_counter()
            est.fit(X, y)
            metrics["fit_time"] = time.perf_counter() - t0
            metrics["train-score"] = float(est.score(X, y))
            for name, ds in trainer.datasets.items():
                if name == "train":
                    continue
                Xe, ye = _dataset_to_xy(ds, trainer.label_column)
                metrics[f"{name}-score"] = float(est.score(Xe, ye))
            ckpt = Checkpoint.from_dict(
                {MODEL_KEY: pickle.dumps(est),
                 "label_column": trainer.label_column})
            session.report(metrics, checkpoint=ckpt)

        inner = DataParallelTrainer(
            train_loop, train_loop_config=dict(self.params),
            scaling_config=self.scaling_config, run_config=self.run_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)
        return inner._fit_internal(report_through_session)

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        return pickle.loads(checkpoint.to_dict()[MODEL_KEY])
