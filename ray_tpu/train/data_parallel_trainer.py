"""DataParallelTrainer + BaseTrainer.

Reference analogue: train/base_trainer.py:339 (fit wraps into a Tune
trainable) and train/data_parallel_trainer.py:56/329 (training_loop drives
BackendExecutor). The framework backend is JAX: gang workers form an SPMD
island via jax.distributed; inside the island the train_func sees the full
mesh and uses pjit/psum — no NCCL, no DDP wrappers.
"""

from __future__ import annotations

import logging
import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.train._internal.backend_executor import (BackendExecutor,
                                                      TrainingFailedError)

logger = logging.getLogger(__name__)


def _emit_train_event(severity: str, label: str, message: str, **fields):
    """Structured train-lifecycle event → local JSONL + GCS event ring.
    Gang restarts land in the same stream as PREEMPTION_NOTICE /
    NODE_DRAINING / WORKER_DIED, so recovery latency (fault → detect →
    resume) is measurable end to end from one event log."""
    try:
        from ray_tpu._private.worker import global_worker
        from ray_tpu.util import events as ev
        w = global_worker()
        ev.report(severity, label, message,
                  gcs_notify=lambda m, p: w.io.run_async(
                      w.gcs.notify(m, p)), **fields)
    except Exception:
        pass


@dataclass
class Result:
    """Reference analogue: ray.air.Result."""
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Any] = None
    error: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def best_checkpoints(self):
        return [(self.checkpoint, self.metrics)] if self.checkpoint else []


class JaxConfig:
    """Backend config (reference analogue: TorchConfig/TensorflowConfig).
    Exists for API parity; island formation itself lives in the executor."""

    def __init__(self, coordinator_port: int = 0):
        self.coordinator_port = coordinator_port


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Wrap into a Tune Trainable (reference: base_trainer.py:365)."""
        from ray_tpu.tune.trainable import FunctionTrainable
        trainer = self

        def _train_fn(config):
            from ray_tpu.air import session
            t = trainer._with_config_overrides(config)
            result = t._fit_internal(report_through_session=True)
            if result.error:
                raise TrainingFailedError(result.error)

        return _train_fn

    def _with_config_overrides(self, config: Dict[str, Any]):
        return self


class DataParallelTrainer(BaseTrainer):
    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint=None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or JaxConfig()

    def _with_config_overrides(self, config: Dict[str, Any]):
        merged = {**self.train_loop_config, **(config or {})}
        return DataParallelTrainer(
            self.train_loop_per_worker, train_loop_config=merged,
            backend_config=self.backend_config,
            scaling_config=self.scaling_config, run_config=self.run_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)

    # ------------------------------------------------------------------- fit

    def fit(self) -> Result:
        return self._fit_internal(report_through_session=False)

    def _checkpoint_root(self) -> Optional[str]:
        """Durable checkpoint root for this run, or None (in-band
        checkpoints, the pre-engine behavior). A run opts into the engine
        by having a stable identity: RunConfig.storage_path and/or name."""
        rc = self.run_config
        if rc.storage_path is None and rc.name is None:
            return None
        base = rc.storage_path or os.environ.get(
            "RTPU_RESULTS_DIR", os.path.expanduser("~/ray_tpu_results"))
        return os.path.join(os.path.expanduser(base), rc.name or "train",
                            "checkpoints")

    def _make_checkpoint_manager(self):
        root = self._checkpoint_root()
        if root is None:
            return None
        from ray_tpu.checkpoint import CheckpointManager
        return CheckpointManager(
            root, checkpoint_config=self.run_config.checkpoint_config)

    def _fit_internal(self, report_through_session: bool) -> Result:
        failure_cfg = self.run_config.failure_config
        attempts_left = max(failure_cfg.max_failures, 0)
        infinite = failure_cfg.max_failures == -1
        checkpoint = self.resume_from_checkpoint
        manager = self._make_checkpoint_manager()
        if checkpoint is None and manager is not None:
            # gang restart across driver restarts: resume from the newest
            # fully-committed step (partial/corrupt steps are skipped)
            latest = manager.latest_committed()
            if latest is not None:
                checkpoint = manager.load(latest)
                logger.info("resuming from committed checkpoint step %d",
                            latest)
        attempt = 0
        while True:
            try:
                return self._run_once(checkpoint, report_through_session,
                                      manager, is_restart=attempt > 0)
            except TrainingFailedError as e:
                logger.warning("training attempt failed: %s", e)
                if not infinite and attempts_left <= 0:
                    return Result(error=str(e), checkpoint=checkpoint)
                attempts_left -= 1
                attempt += 1
                latest = None
                if manager is not None:
                    # a worker that died mid-save leaves an uncommitted
                    # tmp/step dir — latest_committed() skips it, so the
                    # gang restarts from the last *intact* step
                    latest = manager.latest_committed()
                    checkpoint = (manager.load(latest)
                                  if latest is not None
                                  else self.resume_from_checkpoint)
                else:
                    checkpoint = self._latest_checkpoint or checkpoint
                _emit_train_event(
                    "WARNING", "TRAIN_GANG_RESTART",
                    f"gang restart (attempt {attempt}) from committed "
                    f"step {latest}: {e}",
                    attempt=attempt, resumed_step=latest,
                    run_name=self.run_config.name or "")
                logger.warning(
                    "restarting gang from last checkpoint (%s retries left)",
                    "inf" if infinite else attempts_left)

    def _run_once(self, checkpoint, report_through_session: bool,
                  manager=None, is_restart: bool = False) -> Result:
        from ray_tpu.air import session as air_session
        executor = BackendExecutor(self.scaling_config, self.backend_config)
        self._latest_checkpoint = checkpoint
        trial_id = uuid.uuid4().hex[:8]
        ckpt_start_step = 0
        if manager is not None:
            latest = manager.latest_committed()
            ckpt_start_step = latest + 1 if latest is not None else 0
        try:
            executor.start()
            dataset_shards = self._shard_datasets(
                self.scaling_config.num_workers)
            executor.start_training(
                self.train_loop_per_worker, self.train_loop_config,
                checkpoint=checkpoint, dataset_shards=dataset_shards,
                trial_info={"trial_id": trial_id,
                            "trial_name": self.run_config.name or
                            f"train-{trial_id}"},
                checkpoint_root=manager.root if manager else None,
                ckpt_start_step=ckpt_start_step)
            history: List[Dict[str, Any]] = []
            last_metrics: Dict[str, Any] = {}
            while True:
                round_results = executor.get_next_results()
                if is_restart:
                    # first round after a gang restart: the run is live
                    # again — this event closes the recovery window that
                    # opened at the fault (PREEMPTION_NOTICE/WORKER_DIED)
                    is_restart = False
                    _emit_train_event(
                        "INFO", "TRAIN_RESUMED",
                        "gang resumed after restart",
                        run_name=self.run_config.name or "",
                        ckpt_start_step=ckpt_start_step)
                if round_results is None:
                    break
                rank0 = round_results[0]
                last_metrics = rank0.metrics
                history.append(rank0.metrics)
                ckpt = next((r.checkpoint for r in round_results
                             if r.checkpoint is not None), None)
                if ckpt is not None:
                    ckpt = self._commit_round_checkpoint(
                        executor, manager, round_results, ckpt)
                if ckpt is not None:
                    self._latest_checkpoint = ckpt
                if report_through_session and air_session.in_session():
                    air_session.report(rank0.metrics,
                                       checkpoint=self._latest_checkpoint)
                if self._should_stop(last_metrics):
                    break
            return Result(metrics=last_metrics,
                          checkpoint=self._latest_checkpoint,
                          metrics_history=history)
        finally:
            executor.shutdown()

    def _commit_round_checkpoint(self, executor, manager, round_results,
                                 ckpt):
        """Seal a staged step once the whole gang has reported it. The
        round itself is the barrier: every rank staged (sync dict payloads)
        or enqueued (async sharded saves) before reporting; we wait out
        in-flight writers, then commit atomically. Returns the committed
        directory-backed Checkpoint, or None if the step can't be sealed
        (the previous intact step stays latest)."""
        from ray_tpu.checkpoint import PendingCheckpoint
        if not isinstance(ckpt, PendingCheckpoint):
            return ckpt  # in-band payload (no manager configured)
        if manager is None:
            logger.warning("dropping PendingCheckpoint(step=%d): driver "
                           "has no checkpoint manager", ckpt.step)
            return None
        step = max(r.checkpoint.step for r in round_results
                   if isinstance(r.checkpoint, PendingCheckpoint))
        try:
            executor.wait_for_checkpoints()
            manager.commit_step(step)
            return manager.load(step)
        except Exception as e:  # noqa: BLE001 — a torn save must not
            # kill training; the previous committed step remains latest
            logger.warning("checkpoint step %d failed to commit: %r",
                           step, e)
            return None

    def _should_stop(self, metrics: Dict[str, Any]) -> bool:
        stop = self.run_config.stop
        if not stop:
            return False
        for k, v in stop.items():
            if k in metrics and metrics[k] >= v:
                return True
        return False

    def _shard_datasets(self, num_workers: int) -> Dict[str, Any]:
        """Split each dataset into per-worker shards (reference:
        RayDatasetSpec.get_dataset_shards).  With the streaming executor
        enabled, shards of a pending map chain carry the un-executed
        stages (Dataset.streaming_split) so each worker pipelines its own
        ingest instead of waiting for a driver-side materialization."""
        from ray_tpu.data._internal.streaming_executor import (
            streaming_enabled)
        out: Dict[str, Any] = {}
        for name, ds in self.datasets.items():
            if streaming_enabled() and hasattr(ds, "streaming_split"):
                out[name] = ds.streaming_split(num_workers)
            elif hasattr(ds, "split"):
                out[name] = ds.split(num_workers)
            else:
                out[name] = ds
        return out
