"""ray:// remote connectivity (reference: python/ray/util/client/).

Usage: ``ray_tpu.init("ray://head-host:10001")`` on any machine that can
reach the head; the public API (remote/get/put/wait/actors) then routes
over the client protocol. Server side: ``ClientServer`` in a process with
a real driver connection (``ray-tpu start --head --ray-client-server-port
10001`` starts one).
"""

from ray_tpu.util.client.common import (  # noqa: F401
    ClientActorHandle, ClientObjectRef)
from ray_tpu.util.client.worker import (  # noqa: F401
    ClientWorker, client_mode, connect, disconnect)
