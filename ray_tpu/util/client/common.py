"""Client-side handle types for ray:// connections.

Reference analogue: python/ray/util/client/common.py (ClientObjectRef:104,
ClientActorHandle, ClientRemoteFunc). Handles hold only an id; every
operation rides the msgpack protocol to the client server, which owns the
real refs/handles.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


def _current_client():
    from ray_tpu.util.client import worker as client_worker
    c = client_worker._client
    if c is None:
        raise RuntimeError("no ray:// client connection active")
    return c


# Set by the server while deserializing client payloads so that pickled
# client handles resolve to the server-side real objects (see
# server.py _resolve_ref / _resolve_actor).
_server_resolver = threading.local()


def _rehydrate_ref(ref_hex: str):
    """Unpickle hook for ClientObjectRef: on the server this returns the
    REAL ObjectRef from the connection's table; on a client process it
    rebuilds a client ref."""
    resolver = getattr(_server_resolver, "table", None)
    if resolver is not None:
        return resolver.resolve_ref(ref_hex)
    return ClientObjectRef(ref_hex, owned=False)


def _rehydrate_actor(actor_hex: str, class_name: str):
    resolver = getattr(_server_resolver, "table", None)
    if resolver is not None:
        return resolver.resolve_actor(actor_hex)
    return ClientActorHandle(actor_hex, class_name)


class ClientObjectRef:
    """A future living in the cluster, named by the server-side ref hex."""

    def __init__(self, ref_hex: str, owned: bool = True):
        self._hex = ref_hex
        self._owned = owned

    def hex(self) -> str:
        return self._hex

    def binary(self) -> bytes:
        return bytes.fromhex(self._hex)

    def __hash__(self):
        return hash(self._hex)

    def __eq__(self, other):
        return isinstance(other, ClientObjectRef) and \
            other._hex == self._hex

    def __repr__(self):
        return f"ClientObjectRef({self._hex[:16]})"

    def __reduce__(self):
        return (_rehydrate_ref, (self._hex,))

    def __del__(self):
        if not self._owned:
            return
        try:
            from ray_tpu.util.client import worker as client_worker
            c = client_worker._client
            if c is not None and c.connected:
                c.release(self._hex)
        except Exception:
            pass

    def future(self):
        import concurrent.futures
        fut: "concurrent.futures.Future" = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(_current_client().get([self], timeout=None)[0])
            except BaseException as e:
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut


class ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._name = name
        self._options = options or {}

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        return _current_client().actor_call(
            self._handle._hex, self._name, args, kwargs)

    def options(self, **opts) -> "ClientActorMethod":
        return ClientActorMethod(self._handle, self._name, opts)

    def __call__(self, *a, **k):
        raise TypeError(f"Actor methods cannot be called directly; use "
                        f".{self._name}.remote()")


class ClientActorHandle:
    def __init__(self, actor_hex: str, class_name: str = ""):
        self._hex = actor_hex
        self._class_name = class_name

    @property
    def _id_hex(self) -> str:
        return self._hex

    def __getattr__(self, name: str) -> ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self, name)

    def __repr__(self):
        return f"ClientActorHandle({self._class_name}, {self._hex[:12]})"

    def __reduce__(self):
        return (_rehydrate_actor, (self._hex, self._class_name))


