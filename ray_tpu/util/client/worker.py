"""Client-side ray:// connection: routes the public API over the wire.

Reference analogue: python/ray/util/client/worker.py:81 (Worker.connect,
get :225, put :379, remote :508). The transport is the same msgpack
protocol the rest of the control plane speaks (protocol.py) instead of
gRPC; values cross as cloudpickle payloads with handle types swapped at
(de)serialization boundaries (common.py rehydrate hooks).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu._private import protocol
from ray_tpu.util.client.common import ClientActorHandle, ClientObjectRef

_client: Optional["ClientWorker"] = None


def client_mode() -> Optional["ClientWorker"]:
    return _client


class ClientWorker:
    def __init__(self, address: str, namespace: str = "",
                 timeout: float = 30.0):
        # address: "host:port" (the ray:// prefix already stripped)
        self._address = address
        self._io = protocol.EventLoopThread("ray-client")
        self._conn = self._io.run(protocol.connect(address))
        self._lock = threading.Lock()
        self._fn_keys: Dict[str, str] = {}  # content sha -> server key
        self.connected = True
        self.namespace = namespace
        info = self._call("client_hello", {"namespace": namespace},
                          timeout=timeout)
        self.server_info = info

    # ------------------------------------------------------------ plumbing

    def _call(self, method: str, payload: Any,
              timeout: Optional[float] = 120.0) -> Any:
        if not self.connected:
            raise ConnectionError("ray:// client disconnected")
        return self._io.run(
            self._conn.call(method, payload, timeout=timeout),
            timeout=(timeout + 10) if timeout else None)

    def disconnect(self):
        self.connected = False
        try:
            self._conn.close()
        finally:
            self._io.stop()

    # ------------------------------------------------------------- objects

    def put(self, value: Any) -> ClientObjectRef:
        if isinstance(value, (ClientObjectRef, ClientActorHandle)):
            raise TypeError("put() of a ref/handle is not allowed "
                            "(same restriction as the reference client)")
        data = cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        ref_hex = self._call("client_put", {"data": data})
        return ClientObjectRef(ref_hex)

    def get(self, refs: Sequence[ClientObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        out = self._call("client_get",
                         {"ids": [r.hex() for r in refs],
                          "timeout": timeout},
                         timeout=None if timeout is None
                         else timeout + 30.0)
        values = []
        for item in out:
            if item.get("error") is not None:
                raise cloudpickle.loads(item["error"])
            values.append(cloudpickle.loads(item["data"]))
        return values

    def wait(self, refs: Sequence[ClientObjectRef], num_returns: int,
             timeout: Optional[float]
             ) -> Tuple[List[ClientObjectRef], List[ClientObjectRef]]:
        by_hex = {r.hex(): r for r in refs}
        out = self._call("client_wait",
                         {"ids": [r.hex() for r in refs],
                          "num_returns": num_returns, "timeout": timeout},
                         timeout=None if timeout is None
                         else timeout + 30.0)
        ready = [by_hex[h] for h in out["ready"]]
        not_ready = [by_hex[h] for h in out["not_ready"]]
        return ready, not_ready

    def release(self, ref_hex: str):
        try:
            self._io.run(
                self._conn.notify("client_release", {"ids": [ref_hex]}),
                timeout=5.0)
        except Exception:
            pass

    # --------------------------------------------------------------- tasks

    def _export_fn(self, fn, kind: str) -> str:
        # cache by CONTENT hash — an id(fn) key outlives the function
        # object and a reused address would submit the wrong code
        import hashlib
        data = cloudpickle.dumps(fn, protocol=pickle.HIGHEST_PROTOCOL)
        sha = hashlib.sha256(data).hexdigest()
        key = self._fn_keys.get(sha)
        if key is None:
            key = self._call("client_export", {"data": data, "kind": kind})
            self._fn_keys[sha] = key
        return key

    def submit_fn(self, fn, args, kwargs, opts: Dict[str, Any]):
        key = self._export_fn(fn, "fn")
        payload = {
            "key": key,
            "args": cloudpickle.dumps((list(args), kwargs),
                                      protocol=pickle.HIGHEST_PROTOCOL),
            "opts": _clean_opts(opts),
        }
        ref_hexes = self._call("client_task", payload)
        refs = [ClientObjectRef(h) for h in ref_hexes]
        num_returns = opts.get("num_returns")
        if num_returns is None or num_returns == 1:
            return refs[0]
        return refs

    # -------------------------------------------------------------- actors

    def create_actor(self, cls, args, kwargs,
                     opts: Dict[str, Any]) -> ClientActorHandle:
        key = self._export_fn(cls, "cls")
        payload = {
            "key": key,
            "class_name": cls.__name__,
            "args": cloudpickle.dumps((list(args), kwargs),
                                      protocol=pickle.HIGHEST_PROTOCOL),
            "opts": _clean_opts(opts),
        }
        actor_hex = self._call("client_actor_create", payload)
        return ClientActorHandle(actor_hex, cls.__name__)

    def actor_call(self, actor_hex: str, method: str, args,
                   kwargs) -> ClientObjectRef:
        payload = {
            "actor_id": actor_hex,
            "method": method,
            "args": cloudpickle.dumps((list(args), kwargs),
                                      protocol=pickle.HIGHEST_PROTOCOL),
        }
        ref_hex = self._call("client_actor_call", payload)
        return ClientObjectRef(ref_hex)

    def cancel(self, ref_hex: str, force: bool = False):
        self._call("client_cancel", {"id": ref_hex, "force": force})

    def kill_actor(self, actor_hex: str, no_restart: bool = True):
        self._call("client_actor_kill",
                   {"actor_id": actor_hex, "no_restart": no_restart})

    def get_named_actor(self, name: str,
                        namespace: Optional[str]) -> ClientActorHandle:
        out = self._call("client_get_actor",
                         {"name": name, "namespace": namespace})
        if out.get("error"):
            raise ValueError(out["error"])
        return ClientActorHandle(out["actor_id"],
                                 out.get("class_name", ""))

    # ------------------------------------------------------------- cluster

    def cluster_info(self, kind: str) -> Any:
        return self._call("client_cluster_info", {"kind": kind})


def _clean_opts(opts: Dict[str, Any]) -> Dict[str, Any]:
    """Only msgpack-able option values cross the wire."""
    out = {}
    for k, v in (opts or {}).items():
        if isinstance(v, (str, int, float, bool, type(None))):
            out[k] = v
        elif isinstance(v, dict):
            out[k] = _clean_opts(v)
        elif isinstance(v, (list, tuple)):
            out[k] = list(v)
    return out


def connect(address: str, namespace: str = "") -> ClientWorker:
    """Establish the global ray:// connection (address without scheme)."""
    global _client
    if _client is not None and _client.connected:
        raise RuntimeError("ray:// client already connected")
    _client = ClientWorker(address, namespace=namespace)
    return _client


def disconnect():
    global _client
    if _client is not None:
        _client.disconnect()
        _client = None
