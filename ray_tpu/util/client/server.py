"""Client server: hosts ray:// connections inside a cluster process.

Reference analogue: python/ray/util/client/server/server.py (RayletServicer)
+ server/proxier.py. This server runs in a process that has a real driver
connection (ray_tpu.init() already done — e.g. the head started with
``ray-tpu start --head --ray-client-server-port``); each client connection
gets its own table of real ObjectRefs/ActorHandles, freed wholesale on
disconnect. Client payloads are cloudpickle; real refs embedded in results
are swapped for client refs at serialization time (reducer_override) and
back at deserialization time (common._server_resolver).
"""

from __future__ import annotations

import hashlib
import io
import pickle
import threading
from typing import Any, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu._private import protocol, worker as worker_mod
from ray_tpu.util.client import common as client_common


class _ConnTable:
    """Per-connection real-object tables (the server-side ownership)."""

    def __init__(self):
        self.refs: Dict[str, Any] = {}  # ref hex -> real ObjectRef
        self.actors: Dict[str, Any] = {}  # actor hex -> real ActorHandle
        self.exports: Dict[str, Any] = {}  # sha -> (deserialized fn/cls, kind)

    def track_ref(self, ref) -> str:
        h = ref.hex()
        self.refs[h] = ref
        return h

    def resolve_ref(self, ref_hex: str):
        try:
            return self.refs[ref_hex]
        except KeyError:
            raise KeyError(f"unknown client ref {ref_hex[:16]} "
                           "(already released?)")

    def track_actor(self, handle) -> str:
        h = handle._id_hex
        self.actors[h] = handle
        return h

    def resolve_actor(self, actor_hex: str):
        try:
            return self.actors[actor_hex]
        except KeyError:
            raise KeyError(f"unknown client actor {actor_hex[:16]}")


class _ServerPickler(cloudpickle.CloudPickler):
    """Swaps real ObjectRefs/ActorHandles in outgoing values for client
    handles, registering them in the connection table on the way out."""

    def __init__(self, file, table: _ConnTable, **kw):
        super().__init__(file, **kw)
        self._table = table

    def reducer_override(self, obj):
        from ray_tpu._private.worker import ObjectRef
        from ray_tpu.actor import ActorHandle
        if isinstance(obj, ObjectRef):
            self._table.track_ref(obj)
            return (client_common._rehydrate_ref, (obj.hex(),))
        if isinstance(obj, ActorHandle):
            self._table.track_actor(obj)
            return (client_common._rehydrate_actor,
                    (obj._id_hex, obj._class_name))
        return NotImplemented


def _server_dumps(value: Any, table: _ConnTable) -> bytes:
    buf = io.BytesIO()
    _ServerPickler(buf, table, protocol=pickle.HIGHEST_PROTOCOL).dump(value)
    return buf.getvalue()


def _server_loads(data: bytes, table: _ConnTable) -> Any:
    client_common._server_resolver.table = table
    try:
        return cloudpickle.loads(data)
    finally:
        client_common._server_resolver.table = None


class ClientServer:
    """Serves ray:// clients on a TCP port. Blocking cluster calls run on
    a per-server executor thread pool so the protocol loop stays live."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        if not ray_tpu.is_initialized():
            raise RuntimeError("ClientServer requires ray_tpu.init() first")
        from concurrent.futures import ThreadPoolExecutor
        self._exec = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="ray-client-srv")
        self._io = protocol.EventLoopThread("ray-client-server")
        self._server = protocol.Server(self._handlers())
        self.port = self._io.run(self._server.start_tcp(host, port))

    # every handler: payload, conn -> result (async); blocking work hops
    # to the executor
    def _handlers(self):
        async def _run(fn, *args):
            import asyncio
            return await asyncio.get_running_loop().run_in_executor(
                self._exec, fn, *args)

        def table(conn) -> _ConnTable:
            t = conn.meta.get("client_table")
            if t is None:
                t = conn.meta["client_table"] = _ConnTable()
            return t

        async def client_hello(payload, conn):
            table(conn)
            return {"version": ray_tpu.__version__,
                    "namespace": payload.get("namespace", "")}

        async def client_put(payload, conn):
            t = table(conn)

            def _do():
                value = _server_loads(payload["data"], t)
                return t.track_ref(ray_tpu.put(value))
            return await _run(_do)

        async def client_get(payload, conn):
            t = table(conn)

            def _do():
                out = []
                for h in payload["ids"]:
                    try:
                        ref = t.resolve_ref(h)
                        value = ray_tpu.get(ref,
                                            timeout=payload.get("timeout"))
                        out.append({"data": _server_dumps(value, t),
                                    "error": None})
                    except BaseException as e:  # ships to the client
                        out.append({"data": None,
                                    "error": cloudpickle.dumps(e)})
                return out
            return await _run(_do)

        async def client_wait(payload, conn):
            t = table(conn)

            def _do():
                refs = [t.resolve_ref(h) for h in payload["ids"]]
                ready, not_ready = ray_tpu.wait(
                    refs, num_returns=payload["num_returns"],
                    timeout=payload.get("timeout"))
                return {"ready": [r.hex() for r in ready],
                        "not_ready": [r.hex() for r in not_ready]}
            return await _run(_do)

        async def client_release(payload, conn):
            t = table(conn)
            for h in payload.get("ids", []):
                t.refs.pop(h, None)

        async def client_export(payload, conn):
            # exports live in the per-connection table (freed on
            # disconnect) so a long-lived head serving many client
            # sessions doesn't grow memory without bound; the client's
            # sha->key cache is per-connection too, so re-export after
            # reconnect is automatic.
            t = table(conn)

            def _do():
                sha = hashlib.sha256(payload["data"]).hexdigest()[:32]
                if sha not in t.exports:
                    t.exports[sha] = (cloudpickle.loads(payload["data"]),
                                      payload.get("kind", "fn"))
                return sha
            return await _run(_do)

        async def client_task(payload, conn):
            t = table(conn)

            def _do():
                fn, _ = t.exports[payload["key"]]
                args, kwargs = _server_loads(payload["args"], t)
                opts = payload.get("opts") or {}
                rf = ray_tpu.remote(fn) if not opts else \
                    ray_tpu.remote(**opts)(fn)
                refs = rf.remote(*args, **kwargs)
                if not isinstance(refs, list):
                    refs = [refs]
                return [t.track_ref(r) for r in refs]
            return await _run(_do)

        async def client_actor_create(payload, conn):
            t = table(conn)

            def _do():
                cls, _ = t.exports[payload["key"]]
                args, kwargs = _server_loads(payload["args"], t)
                opts = payload.get("opts") or {}
                ac = ray_tpu.remote(cls) if not opts else \
                    ray_tpu.remote(**opts)(cls)
                handle = ac.remote(*args, **kwargs)
                return t.track_actor(handle)
            return await _run(_do)

        async def client_actor_call(payload, conn):
            t = table(conn)

            def _do():
                handle = t.resolve_actor(payload["actor_id"])
                args, kwargs = _server_loads(payload["args"], t)
                method = getattr(handle, payload["method"])
                return t.track_ref(method.remote(*args, **kwargs))
            return await _run(_do)

        async def client_cancel(payload, conn):
            t = table(conn)

            def _do():
                ref = t.resolve_ref(payload["id"])
                ray_tpu.cancel(ref, force=payload.get("force", False))
                return True
            return await _run(_do)

        async def client_actor_kill(payload, conn):
            t = table(conn)

            def _do():
                handle = t.resolve_actor(payload["actor_id"])
                ray_tpu.kill(handle,
                             no_restart=payload.get("no_restart", True))
                return True
            return await _run(_do)

        async def client_get_actor(payload, conn):
            t = table(conn)

            def _do():
                try:
                    handle = ray_tpu.get_actor(
                        payload["name"],
                        namespace=payload.get("namespace"))
                except ValueError as e:
                    return {"error": str(e)}
                return {"actor_id": t.track_actor(handle),
                        "class_name": handle._class_name}
            return await _run(_do)

        # ---- raw (msgpack-native) surface for non-Python clients ----
        # Values ride as protocol-native msgpack structures, no pickling;
        # tasks are invoked by cross_language registry name (reference:
        # the Java/C++ workers' named-function invocation).

        async def client_put_raw(payload, conn):
            t = table(conn)

            def _do():
                return t.track_ref(ray_tpu.put(payload["value"]))
            return await _run(_do)

        async def client_get_raw(payload, conn):
            t = table(conn)

            def _do():
                out = []
                for h in payload["ids"]:
                    try:
                        value = ray_tpu.get(t.resolve_ref(h),
                                            timeout=payload.get("timeout"))
                        out.append({"value": value, "error": None})
                    except BaseException as e:
                        out.append({"value": None,
                                    "error": f"{type(e).__name__}: {e}"})
                return out
            return await _run(_do)

        async def client_call_named(payload, conn):
            t = table(conn)

            def _do():
                from ray_tpu.util import cross_language
                fn = cross_language.get_function(payload["name"])
                opts = payload.get("opts") or {}
                rf = ray_tpu.remote(fn) if not opts else \
                    ray_tpu.remote(**opts)(fn)
                args = payload.get("args") or []
                refs = rf.remote(*args)
                if not isinstance(refs, list):
                    refs = [refs]
                return [t.track_ref(r) for r in refs]
            return await _run(_do)

        async def client_list_named(payload, conn):
            from ray_tpu.util import cross_language
            return cross_language.list_functions()

        async def client_kv(payload, conn):
            def _do():
                from ray_tpu._private import worker as wm
                w = wm.global_worker()
                op = payload["op"]
                if op == "put":
                    w.call_sync(w.gcs, "kv_put",
                                {"key": payload["key"],
                                 "value": payload["value"],
                                 "overwrite": True})
                    return True
                if op == "get":
                    r = w.call_sync(w.gcs, "kv_get",
                                    {"key": payload["key"]})
                    return r.get("value")
                raise ValueError(f"bad kv op {op!r}")
            return await _run(_do)

        async def client_cluster_info(payload, conn):
            def _do():
                kind = payload["kind"]
                if kind == "cluster_resources":
                    return ray_tpu.cluster_resources()
                if kind == "available_resources":
                    return ray_tpu.available_resources()
                if kind == "nodes":
                    return ray_tpu.nodes()
                raise ValueError(f"unknown cluster info kind {kind!r}")
            return await _run(_do)

        async def _on_disconnect(conn):
            # wholesale release of the client's refs (owner-side GC kicks
            # in when the table entries drop)
            conn.meta.pop("client_table", None)

        return {
            "client_hello": client_hello,
            "client_put": client_put,
            "client_get": client_get,
            "client_wait": client_wait,
            "client_release": client_release,
            "client_export": client_export,
            "client_task": client_task,
            "client_cancel": client_cancel,
            "client_actor_create": client_actor_create,
            "client_actor_call": client_actor_call,
            "client_actor_kill": client_actor_kill,
            "client_get_actor": client_get_actor,
            "client_put_raw": client_put_raw,
            "client_get_raw": client_get_raw,
            "client_call_named": client_call_named,
            "client_list_named": client_list_named,
            "client_kv": client_kv,
            "client_cluster_info": client_cluster_info,
            "_on_disconnect": _on_disconnect,
        }

    def stop(self):
        self._server.close()
        self._io.stop()
        self._exec.shutdown(wait=False)
