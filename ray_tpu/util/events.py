"""Structured cluster events (the RAY_EVENT framework analogue).

Reference analogue: src/ray/util/event.cc + dashboard event browsing —
components emit typed, severity-tagged events; each process appends them
to a JSONL file under the session dir AND ships them to the GCS, which
keeps a bounded ring visible through the state API, the dashboard
(/api/events), and `ray-tpu events`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

DEBUG, INFO, WARNING, ERROR, FATAL = (
    "DEBUG", "INFO", "WARNING", "ERROR", "FATAL")

_lock = threading.Lock()
_file = None
_source = "unknown"


def init_emitter(source: str, session_dir: Optional[str] = None):
    """Per-process setup: names the component and opens its JSONL log."""
    global _file, _source
    _source = source
    if session_dir:
        d = os.path.join(session_dir, "logs", "events")
        os.makedirs(d, exist_ok=True)
        with _lock:
            if _file is not None:
                try:
                    _file.close()
                except Exception:
                    pass
            _file = open(os.path.join(
                d, f"events_{source}_{os.getpid()}.log"), "a")


def make_event(severity: str, label: str, message: str,
               **fields) -> Dict[str, Any]:
    return {"timestamp": time.time(), "severity": severity,
            "source": _source, "pid": os.getpid(), "label": label,
            "message": message, "fields": fields}


def emit_local(event: Dict[str, Any]):
    """Append to this process's event log (always safe to call)."""
    with _lock:
        if _file is None:
            return
        try:
            json.dump(event, _file, default=str)
            _file.write("\n")
            _file.flush()
        except Exception:
            pass


def report(severity: str, label: str, message: str,
           gcs_notify=None, **fields) -> Dict[str, Any]:
    """Record an event locally and (best-effort) ship it to the GCS.

    ``gcs_notify(method, payload)`` is the caller's fire-and-forget GCS
    channel (worker.try_notify / raylet's connection) — None for the GCS
    itself, which stores directly."""
    ev = make_event(severity, label, message, **fields)
    emit_local(ev)
    if gcs_notify is not None:
        try:
            gcs_notify("add_event", ev)
        except Exception:
            pass
    return ev
