"""Cross-language function registry.

Reference analogue: python/ray/cross_language.py + the Java/C++ workers'
named-function invocation: a non-Python driver cannot ship pickled
callables, so it invokes functions a Python process REGISTERED by name,
with msgpack-native argument/return values (the wire format the whole
control plane already speaks).

    # Python side (e.g. the process running the ray:// client server)
    from ray_tpu.util import cross_language
    cross_language.register_function("math.add", lambda a, b: a + b)

    // C++ side (src/cpp_client/ray_tpu_client.hpp)
    auto ref = client.CallNamed("math.add", {mp::Int(1), mp::Int(41)});
    int v = client.Get(ref).AsInt();   // 42
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

_lock = threading.Lock()
_registry: Dict[str, Callable] = {}


def register_function(name: str, fn: Callable):
    """Expose ``fn`` to non-Python drivers under ``name``."""
    if not callable(fn):
        raise TypeError("fn must be callable")
    with _lock:
        _registry[name] = fn


def unregister_function(name: str):
    with _lock:
        _registry.pop(name, None)


def get_function(name: str) -> Callable:
    with _lock:
        fn = _registry.get(name)
    if fn is None:
        raise KeyError(
            f"no cross-language function registered as {name!r} "
            f"(known: {sorted(_registry)})")
    return fn


def list_functions() -> List[str]:
    with _lock:
        return sorted(_registry)
