"""Serializability inspector.

Reference analogue: python/ray/util/check_serialize.py
(inspect_serializability) — recursively locate the members of an object
that fail cloudpickle, instead of one opaque PicklingError.
"""

from __future__ import annotations

import inspect
from typing import Any, Set, Tuple

import cloudpickle


class FailureTuple:
    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple({self.name!r} from {self.parent!r})"


def _check(obj: Any) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def inspect_serializability(
        obj: Any, name: str = "object", depth: int = 3,
        _parent: Any = None,
        _failures: Set[int] = None,
        _out: list = None) -> Tuple[bool, list]:
    """Return (serializable, [FailureTuple...]): the deepest members that
    fail pickling."""
    if _out is None:
        _out = []
    if _failures is None:
        _failures = set()
    if _check(obj):
        return True, _out
    found_deeper = False
    if depth > 0:
        members: list = []
        if inspect.isfunction(obj):
            closure = inspect.getclosurevars(obj)
            members = list(closure.nonlocals.items()) + \
                list(closure.globals.items())
        elif hasattr(obj, "__dict__") and isinstance(obj.__dict__, dict):
            members = list(obj.__dict__.items())
        elif isinstance(obj, dict):
            members = list(obj.items())
        elif isinstance(obj, (list, tuple, set)):
            members = [(f"[{i}]", v) for i, v in enumerate(obj)]
        for mname, member in members:
            # the recursive call re-checks the member itself, so no
            # pre-filter pickle here (would double the diagnostic cost)
            ok, _ = inspect_serializability(
                member, name=str(mname), depth=depth - 1,
                _parent=obj, _failures=_failures, _out=_out)
            if not ok:
                found_deeper = True
    if not found_deeper and id(obj) not in _failures:
        _failures.add(id(obj))
        _out.append(FailureTuple(obj, name, _parent))
    return False, _out
