"""joblib backend: sklearn's n_jobs parallelism over cluster tasks.

Reference analogue: python/ray/util/joblib/ (register_ray +
ray_backend.py) — a joblib ParallelBackend whose apply_async submits to
the cluster, so `with joblib.parallel_backend("ray_tpu"):` fans
GridSearchCV / cross_val_score / any joblib-parallel workload across
nodes unchanged.
"""

from __future__ import annotations

from typing import Any, Callable


class _Result:
    """joblib future protocol: .get(timeout=None)."""

    def __init__(self, ref, callback=None):
        self._ref = ref
        if callback is not None:
            # joblib drives completion through the callback
            import threading

            def _wait():
                import ray_tpu
                try:
                    value = ray_tpu.get(ref)
                except BaseException as e:  # delivered via get() below
                    value = e
                callback(value)

            threading.Thread(target=_wait, daemon=True).start()

    def get(self, timeout=None):
        import ray_tpu
        value = ray_tpu.get(self._ref, timeout=timeout)
        if isinstance(value, BaseException):
            raise value
        return value


def register_ray():
    """Register the 'ray_tpu' joblib backend (idempotent)."""
    from joblib import register_parallel_backend
    from joblib.parallel import ParallelBackendBase

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True

        def configure(self, n_jobs=1, parallel=None, **kw):
            import ray_tpu
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs):
            import ray_tpu
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs is None or n_jobs == -1:
                return max(1, cpus)
            return max(1, n_jobs)

        def apply_async(self, func: Callable, callback=None) -> Any:
            import ray_tpu

            # func is a joblib BatchedCalls (picklable); run it whole as
            # one task
            @ray_tpu.remote
            def _run_batch(batch):
                return batch()

            ref = _run_batch.remote(func)
            return _Result(ref, callback)

        def abort_everything(self, ensure_ready=True):
            pass

    register_parallel_backend("ray_tpu", RayTpuBackend)
