"""ray_tpu.util — user utilities (reference: python/ray/util/)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          placement_group_table,
                                          remove_placement_group)
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)

__all__ = [
    "ActorPool", "Queue", "Empty", "Full", "placement_group",
    "PlacementGroup", "remove_placement_group", "placement_group_table",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
]
