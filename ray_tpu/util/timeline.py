"""Chrome-trace timeline events.

Reference analogue: the profile-event pipeline behind `ray timeline`
(core_worker/profiling.cc → StatsGcsService.AddProfileData →
_private/state.py:414 chrome_tracing_dump). Here each worker buffers
task begin/end events locally and pushes them to the GCS KV; the driver
merges all per-process buffers into one chrome://tracing JSON.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List

_events: List[Dict[str, Any]] = []
_lock = threading.Lock()
_MAX_EVENTS = 10_000  # ring-buffer cap: bounds memory + kv payload
_total_recorded = 0  # monotonic: dirty-check survives ring trimming
_dropped = 0  # monotonic: events the ring trimmed (surfaced in dumps)
_flusher_started = False
_flusher_stop = None


def _ensure_flusher():
    """Background flusher so events recorded just before a worker goes
    idle still reach the GCS (flush-on-record alone would strand them
    inside the min_interval window)."""
    global _flusher_started, _flusher_stop
    if _flusher_started:
        return
    _flusher_started = True
    stop = _flusher_stop = threading.Event()

    def loop():
        while not stop.wait(1.0):
            try:
                flush()
            except Exception:
                pass

    threading.Thread(target=loop, daemon=True,
                     name="rtpu-timeline-flush").start()


def stop_flusher():
    """Worker shutdown hook: end the flusher thread and reset the
    started flag so a reconnect in the same process starts a fresh one
    (the unreset flag leaked one daemon thread per init/shutdown
    cycle)."""
    global _flusher_started, _flusher_stop
    if _flusher_stop is not None:
        _flusher_stop.set()
    _flusher_stop = None
    _flusher_started = False


def dropped_count() -> int:
    with _lock:
        return _dropped


def record(name, ph, ts, pid=0, tid=0, **kw):
    global _total_recorded, _dropped
    with _lock:
        _events.append({"name": name, "ph": ph, "ts": ts, "pid": pid,
                        "tid": tid, **kw})
        _total_recorded += 1
        if len(_events) > _MAX_EVENTS:
            _dropped += len(_events) - _MAX_EVENTS
            del _events[:len(_events) - _MAX_EVENTS]


def record_task(name: str, t0: float, t1: float, pid: int = 0,
                failed: bool = False, trace_ctx: Dict[str, Any] = None):
    """Complete ('X') event per task execution; flushed opportunistically
    to the GCS so the driver can merge cross-process. ``trace_ctx``
    carries the propagated span identifiers (reference:
    tracing_helper.py _DictPropagator riding the TaskSpec) so the merged
    timeline reconstructs the driver→task→child call tree."""
    with _lock:
        _events.append({
            "name": name, "ph": "X", "ts": t0 * 1e6,
            "dur": (t1 - t0) * 1e6, "pid": pid,
            "tid": threading.get_ident() % 1_000_000,
            "cname": "terrible" if failed else None,
            "cat": "task",
            "args": dict(trace_ctx or {}),
        })
        global _total_recorded, _dropped
        _total_recorded += 1
        if len(_events) > _MAX_EVENTS:
            _dropped += len(_events) - _MAX_EVENTS
            del _events[:len(_events) - _MAX_EVENTS]
    # async: the background flusher pushes to GCS so the task-completion
    # path never blocks on a kv_put
    _ensure_flusher()


def collect() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


_last_pushed_total = 0


def flush():
    """Push this process's buffer to GCS KV under a per-pid key (no-op
    when nothing new was recorded since the previous push). Dirty check
    uses the monotonic recorded-event counter — the buffer *length*
    plateaus at the ring cap, which would make a length-based check a
    permanent no-op once 10k events accumulate.

    The cursor only advances AFTER the kv_put succeeds: advancing it
    first turned any failed push (GCS restart window, timeout) into
    silently dropping every event recorded since the last successful
    flush — the next flush would see a clean dirty-check and never
    retry them."""
    global _last_pushed_total
    from ray_tpu._private import worker as worker_mod
    w = worker_mod._global_worker
    if w is None or not w.connected:
        return
    with _lock:
        if _total_recorded == _last_pushed_total:
            return
        events = list(_events)
        if _dropped:
            # ring-trim loss travels WITH the buffer: the merged dump
            # can report "history missing" instead of silently looking
            # complete (metadata event, invisible to the track renderer)
            events.append(_dropped_meta(_dropped))
        snapshot = _total_recorded
    try:
        w.call_sync(w.gcs, "kv_put", {
            "key": f"@timeline/{w.node_id[:8]}-{os.getpid()}",
            "value": json.dumps(events).encode(),
            "overwrite": True}, timeout=5)
    except Exception:
        return  # cursor untouched; the next flush retries these events
    with _lock:
        # concurrent flushes may complete out of order; never regress
        _last_pushed_total = max(_last_pushed_total, snapshot)


def _dropped_meta(n: int) -> Dict[str, Any]:
    return {"name": "rtpu_timeline_dropped", "ph": "M", "ts": 0,
            "pid": os.getpid(), "tid": 0, "args": {"dropped": n}}


def dump_dropped_total(events: List[Dict[str, Any]]) -> int:
    """Sum of ring-trim losses across every process's buffer in a
    merged dump (the dashboard surfaces this next to the timeline)."""
    return sum(int((e.get("args") or {}).get("dropped") or 0)
               for e in events
               if e.get("name") == "rtpu_timeline_dropped")


def timeline_dump() -> List[Dict[str, Any]]:
    """Merge every process's events into one chrome-trace list
    (driver-side; reference: `ray timeline`)."""
    from ray_tpu._private import worker as worker_mod
    flush()
    w = worker_mod._global_worker
    merged: List[Dict[str, Any]] = []
    if w is not None and w.connected:
        try:
            keys = w.call_sync(w.gcs, "kv_keys",
                               {"prefix": "@timeline/"},
                               timeout=10).get("keys", [])
            for k in keys:
                v = w.call_sync(w.gcs, "kv_get", {"key": k},
                                timeout=10).get("value")
                if v:
                    merged.extend(json.loads(v))
        except Exception:
            pass
    if not merged:
        merged = collect()
        with _lock:
            if _dropped:
                merged.append(_dropped_meta(_dropped))
    return [{k: v for k, v in e.items() if v is not None}
            for e in merged]
