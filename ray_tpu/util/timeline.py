"""Minimal chrome-trace event collection (fleshed out with the state API)."""
_events = []


def record(name, ph, ts, pid=0, tid=0, **kw):
    _events.append({"name": name, "ph": ph, "ts": ts, "pid": pid,
                    "tid": tid, **kw})


def collect():
    return list(_events)
