"""Distributed FIFO queue on an actor.

Reference analogue: python/ray/util/queue.py (Queue over an async actor).
"""

from __future__ import annotations

import queue as _pyqueue
import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = _pyqueue.Queue(maxsize=maxsize)

    def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            self._q.put(item, block=timeout is not None and timeout > 0,
                        timeout=timeout or None)
            return True
        except _pyqueue.Full:
            return False

    def get(self, timeout: Optional[float] = None):
        try:
            return True, self._q.get(
                block=timeout is not None and timeout > 0,
                timeout=timeout or None)
        except _pyqueue.Empty:
            return False, None

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()


class Queue:
    """Client facade; safe to pass to tasks/actors (pickles the handle)."""

    def __init__(self, maxsize: int = 0, *, _actor=None):
        if _actor is not None:
            self.actor = _actor
            return
        cls = ray_tpu.remote(max_concurrency=8)(_QueueActor)
        self.actor = cls.remote(maxsize)

    # Blocking semantics are implemented CLIENT-side: each server call
    # blocks at most ~0.2s, so N blocked callers can never starve the
    # queue actor's thread pool and deadlock producers against consumers.
    _POLL = 0.2

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None):
        deadline = (None if (block and timeout is None)
                    else time.monotonic() + (timeout or 0.0))
        while True:
            slice_t = self._POLL if block else 0.0
            if deadline is not None:
                slice_t = min(slice_t, max(0.0, deadline -
                                           time.monotonic()))
            ok = ray_tpu.get(self.actor.put.remote(item, slice_t))
            if ok:
                return
            if not block or (deadline is not None
                             and time.monotonic() >= deadline):
                raise Full("queue full")

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        deadline = (None if (block and timeout is None)
                    else time.monotonic() + (timeout or 0.0))
        while True:
            slice_t = self._POLL if block else 0.0
            if deadline is not None:
                slice_t = min(slice_t, max(0.0, deadline -
                                           time.monotonic()))
            # unbounded inner get is safe: the actor transport guarantees
            # a reply (result or ActorUnavailableError — the send task's
            # last-resort handler) — and a timeout here would DROP an
            # item the actor already dequeued when the reply is merely
            # slow under a backlog
            ok, item = ray_tpu.get(self.actor.get.remote(slice_t))
            if ok:
                return item
            if not block or (deadline is not None
                             and time.monotonic() >= deadline):
                raise Empty("queue empty")

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def __reduce__(self):
        return (_rebuild_queue, (self.actor,))


def _rebuild_queue(actor):
    return Queue(_actor=actor)
