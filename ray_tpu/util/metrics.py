"""User-facing metrics API: Counter / Gauge / Histogram.

Reference analogue: python/ray/util/metrics.py flowing into the C++
stats pipeline (SURVEY.md §5.5). Here metrics aggregate in a named
metrics-hub actor and export in Prometheus text format
(``ray_tpu.util.metrics.prometheus_text()``), which the dashboard
scrapes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import ray_tpu

_HUB_NAME = "METRICS_HUB"
_local_lock = threading.Lock()


class _MetricsHub:
    """Cluster-wide aggregation point (one named actor)."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, tuple], dict] = {}
        self._lock = threading.Lock()

    def record(self, name: str, kind: str, value: float,
               tags: Optional[Dict[str, str]], description: str,
               boundaries: Optional[List[float]] = None):
        key = (name, tuple(sorted((tags or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = {"name": name, "kind": kind, "tags": tags or {},
                     "description": description, "value": 0.0,
                     "count": 0, "sum": 0.0,
                     "boundaries": boundaries or [],
                     "buckets": [0] * (len(boundaries or []) + 1)}
                self._metrics[key] = m
            if kind == "counter":
                m["value"] += value
            elif kind == "gauge":
                m["value"] = value
            else:  # histogram
                m["count"] += 1
                m["sum"] += value
                for i, b in enumerate(m["boundaries"]):
                    if value <= b:
                        m["buckets"][i] += 1
                        break
                else:
                    m["buckets"][-1] += 1

    def dump(self) -> List[dict]:
        with self._lock:
            return [dict(m) for m in self._metrics.values()]


def _hub():
    try:
        return ray_tpu.get_actor(_HUB_NAME)
    except Exception:
        pass
    with _local_lock:
        try:
            return ray_tpu.get_actor(_HUB_NAME)
        except Exception:
            cls = ray_tpu.remote(name=_HUB_NAME, lifetime="detached",
                                 max_concurrency=8)(_MetricsHub)
            try:
                return cls.remote()
            except Exception:
                return ray_tpu.get_actor(_HUB_NAME)


class _Metric:
    KIND = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._boundaries: Optional[List[float]] = None

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _record(self, value: float, tags: Optional[Dict[str, str]]):
        merged = {**self._default_tags, **(tags or {})}
        # fire-and-forget to the hub
        _hub().record.remote(self._name, self.KIND, float(value),
                             merged, self._description,
                             self._boundaries)


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        self._record(value, tags)


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._record(value, tags)


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or [1.0])

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        self._record(value, tags)


def dump_metrics() -> List[dict]:
    return ray_tpu.get(_hub().dump.remote(), timeout=30.0)


def _escape_label(v: str) -> str:
    """Prometheus exposition format requires \\\\, \\\" and newline
    escapes in label values."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def prometheus_text() -> str:
    """Prometheus exposition format of every recorded metric."""
    lines = []
    for m in dump_metrics():
        tag_str = ",".join(f'{k}="{_escape_label(v)}"'
                           for k, v in sorted(m["tags"].items()))
        tag_part = f"{{{tag_str}}}" if tag_str else ""
        if m["kind"] in ("counter", "gauge"):
            lines.append(f"# TYPE {m['name']} {m['kind']}")
            lines.append(f"{m['name']}{tag_part} {m['value']}")
        else:
            lines.append(f"# TYPE {m['name']} histogram")
            acc = 0
            for b, c in zip(m["boundaries"], m["buckets"]):
                acc += c
                sep = "," if tag_str else ""
                lines.append(
                    f'{m["name"]}_bucket{{{tag_str}{sep}le="{b}"}} {acc}')
            sep = "," if tag_str else ""
            lines.append(
                f'{m["name"]}_bucket{{{tag_str}{sep}le="+Inf"}} '
                f'{m["count"]}')
            lines.append(f"{m['name']}_sum{tag_part} {m['sum']}")
            lines.append(f"{m['name']}_count{tag_part} {m['count']}")
    return "\n".join(lines) + "\n"
