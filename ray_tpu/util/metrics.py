"""User-facing metrics API: Counter / Gauge / Histogram.

Reference analogue: python/ray/util/metrics.py flowing into the C++
stats pipeline (SURVEY.md §5.5). Here metrics aggregate in a named
metrics-hub actor and export in Prometheus text format
(``ray_tpu.util.metrics.prometheus_text()``), which the dashboard
scrapes.

Recording is PRE-AGGREGATED process-locally (reference: the per-core-
worker OpenCensus view aggregation before export): each data point
folds into a local table under a plain lock, and a background flusher
ships ONE ``record_batch`` actor call per interval
(``RTPU_METRICS_FLUSH_S``, default 1 s). A hot loop incrementing a
Counter therefore costs a dict update, not a dispatch-plane message
per point. ``RTPU_METRICS_SYNC=1`` restores the old one-call-per-point
behavior (tests that assert immediately after recording).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import ray_tpu

_HUB_NAME = "METRICS_HUB"
_local_lock = threading.Lock()

# process-local pre-aggregation buffer: (name, sorted-tags) -> entry
_pending: Dict[Tuple[str, tuple], dict] = {}
_pending_lock = threading.Lock()
_flusher_started = False


def _sync_mode() -> bool:
    return os.environ.get("RTPU_METRICS_SYNC") == "1"


def _flush_interval() -> float:
    return float(os.environ.get("RTPU_METRICS_FLUSH_S", 1.0))


class _MetricsHub:
    """Cluster-wide aggregation point (one named actor)."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, tuple], dict] = {}
        self._lock = threading.Lock()

    def record(self, name: str, kind: str, value: float,
               tags: Optional[Dict[str, str]], description: str,
               boundaries: Optional[List[float]] = None):
        key = (name, tuple(sorted((tags or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = {"name": name, "kind": kind, "tags": tags or {},
                     "description": description, "value": 0.0,
                     "count": 0, "sum": 0.0,
                     "boundaries": boundaries or [],
                     "buckets": [0] * (len(boundaries or []) + 1)}
                self._metrics[key] = m
            if kind == "counter":
                m["value"] += value
            elif kind == "gauge":
                m["value"] = value
            else:  # histogram
                m["count"] += 1
                m["sum"] += value
                for i, b in enumerate(m["boundaries"]):
                    if value <= b:
                        m["buckets"][i] += 1
                        break
                else:
                    m["buckets"][-1] += 1

    def record_batch(self, entries: List[dict]):
        """Apply pre-aggregated per-process entries in one call: a
        counter entry carries the summed delta, a gauge the last
        value, a histogram its locally-bucketed counts + sum."""
        for e in entries:
            key = (e["name"], tuple(sorted((e.get("tags")
                                            or {}).items())))
            boundaries = e.get("boundaries") or []
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = {"name": e["name"], "kind": e["kind"],
                         "tags": e.get("tags") or {},
                         "description": e.get("description", ""),
                         "value": 0.0, "count": 0, "sum": 0.0,
                         "boundaries": boundaries,
                         "buckets": [0] * (len(boundaries) + 1)}
                    self._metrics[key] = m
                kind = e["kind"]
                if kind == "counter":
                    m["value"] += e.get("value", 0.0)
                elif kind == "gauge":
                    m["value"] = e.get("value", 0.0)
                else:  # histogram: merge bucketed counts
                    m["count"] += e.get("count", 0)
                    m["sum"] += e.get("sum", 0.0)
                    for i, c in enumerate(e.get("buckets") or []):
                        if i < len(m["buckets"]):
                            m["buckets"][i] += c

    def dump(self) -> List[dict]:
        with self._lock:
            return [dict(m) for m in self._metrics.values()]


def _hub():
    try:
        return ray_tpu.get_actor(_HUB_NAME)
    except Exception:
        pass
    with _local_lock:
        try:
            return ray_tpu.get_actor(_HUB_NAME)
        except Exception:
            cls = ray_tpu.remote(name=_HUB_NAME, lifetime="detached",
                                 max_concurrency=8)(_MetricsHub)
            try:
                return cls.remote()
            except Exception:
                return ray_tpu.get_actor(_HUB_NAME)


class _Metric:
    KIND = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._boundaries: Optional[List[float]] = None

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _record(self, value: float, tags: Optional[Dict[str, str]]):
        merged = {**self._default_tags, **(tags or {})}
        if _sync_mode():
            # escape hatch: one fire-and-forget per point (tests)
            _hub().record.remote(self._name, self.KIND, float(value),
                                 merged, self._description,
                                 self._boundaries)
            return
        # pre-aggregate locally; the flusher ships one batch per tick
        key = (self._name, tuple(sorted(merged.items())))
        value = float(value)
        with _pending_lock:
            e = _pending.get(key)
            if e is None:
                e = {"name": self._name, "kind": self.KIND,
                     "tags": merged, "description": self._description,
                     "boundaries": self._boundaries,
                     "value": 0.0, "count": 0, "sum": 0.0,
                     "buckets": [0] * (len(self._boundaries or []) + 1)}
                _pending[key] = e
            if self.KIND == "counter":
                e["value"] += value
            elif self.KIND == "gauge":
                e["value"] = value
            else:  # histogram: bucket locally
                e["count"] += 1
                e["sum"] += value
                for i, b in enumerate(self._boundaries or []):
                    if value <= b:
                        e["buckets"][i] += 1
                        break
                else:
                    e["buckets"][-1] += 1
        _ensure_flusher()


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        self._record(value, tags)


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._record(value, tags)


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or [1.0])

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        self._record(value, tags)


def _drain_pending() -> List[dict]:
    with _pending_lock:
        entries = list(_pending.values())
        _pending.clear()
    return entries


def flush_metrics(sync: bool = True):
    """Ship the process-local aggregation buffer to the hub now. With
    ``sync`` the call is awaited so a dump immediately after sees the
    data; the background flusher uses fire-and-forget."""
    entries = _drain_pending()
    if not entries:
        return
    try:
        ref = _hub().record_batch.remote(entries)
        if sync:
            ray_tpu.get(ref, timeout=30.0)
    except Exception:
        # hub unreachable (e.g. shutdown racing the flusher): requeue
        # nothing — metrics are lossy telemetry, not a ledger
        pass


def _ensure_flusher():
    global _flusher_started
    if _flusher_started:
        return
    with _local_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        while True:
            time.sleep(_flush_interval())
            try:
                flush_metrics(sync=False)
            except Exception:
                pass

    threading.Thread(target=loop, daemon=True,
                     name="rtpu-metrics-flush").start()


def dump_metrics() -> List[dict]:
    flush_metrics(sync=True)
    return ray_tpu.get(_hub().dump.remote(), timeout=30.0)


def _escape_label(v: str) -> str:
    """Prometheus exposition format requires \\\\, \\\" and newline
    escapes in label values."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def prometheus_text() -> str:
    """Prometheus exposition format of every recorded metric."""
    lines = []
    for m in dump_metrics():
        tag_str = ",".join(f'{k}="{_escape_label(v)}"'
                           for k, v in sorted(m["tags"].items()))
        tag_part = f"{{{tag_str}}}" if tag_str else ""
        if m["kind"] in ("counter", "gauge"):
            lines.append(f"# TYPE {m['name']} {m['kind']}")
            lines.append(f"{m['name']}{tag_part} {m['value']}")
        else:
            lines.append(f"# TYPE {m['name']} histogram")
            acc = 0
            for b, c in zip(m["boundaries"], m["buckets"]):
                acc += c
                sep = "," if tag_str else ""
                lines.append(
                    f'{m["name"]}_bucket{{{tag_str}{sep}le="{b}"}} {acc}')
            sep = "," if tag_str else ""
            lines.append(
                f'{m["name"]}_bucket{{{tag_str}{sep}le="+Inf"}} '
                f'{m["count"]}')
            lines.append(f"{m['name']}_sum{tag_part} {m['sum']}")
            lines.append(f"{m['name']}_count{tag_part} {m['count']}")
    return "\n".join(lines) + "\n"
