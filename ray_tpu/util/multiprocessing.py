"""multiprocessing.Pool-compatible API on cluster tasks.

Reference analogue: python/ray/util/multiprocessing/ (Pool over Ray
tasks). map/starmap chunk the iterable into tasks; apply_async returns
an AsyncResult wrapping an ObjectRef.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout: Optional[float] = None):
        return ray_tpu.get(self._ref, timeout=timeout)

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait([self._ref], num_returns=1, timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait([self._ref], num_returns=1, timeout=0)
        return len(ready) == 1

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


def _chunk(seq: List[Any], n_chunks: int) -> List[List[Any]]:
    n = max(1, (len(seq) + n_chunks - 1) // n_chunks)
    return [seq[i:i + n] for i in range(0, len(seq), n)]


class Pool:
    def __init__(self, processes: Optional[int] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        total_cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
        self._size = processes or max(1, total_cpus)
        self._closed = False

    def apply(self, fn: Callable, args: tuple = (),
              kwargs: dict = None) -> Any:
        return self.apply_async(fn, args, kwargs).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwargs: dict = None) -> AsyncResult:
        self._check_open()
        remote_fn = ray_tpu.remote(fn)
        return AsyncResult(remote_fn.remote(*args, **(kwargs or {})))

    def map(self, fn: Callable, iterable: Iterable[Any],
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable[Any],
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        items = list(iterable)
        chunks = (_chunk(items, self._size) if chunksize is None
                  else [items[i:i + chunksize]
                        for i in range(0, len(items), chunksize)])

        @ray_tpu.remote
        def _run_chunk(chunk):
            return [fn(x) for x in chunk]

        refs = [_run_chunk.remote(c) for c in chunks]

        @ray_tpu.remote
        def _gather(*parts):
            return [x for part in parts for x in part]

        return AsyncResult(_gather.remote(*refs))

    def starmap(self, fn: Callable,
                iterable: Iterable[tuple]) -> List[Any]:
        return self.map(lambda args: fn(*args), iterable)

    def imap(self, fn: Callable, iterable: Iterable[Any]):
        self._check_open()
        remote_fn = ray_tpu.remote(fn)
        refs = [remote_fn.remote(x) for x in iterable]
        for r in refs:
            yield ray_tpu.get(r)

    def imap_unordered(self, fn: Callable, iterable: Iterable[Any]):
        self._check_open()
        remote_fn = ray_tpu.remote(fn)
        pending = [remote_fn.remote(x) for x in iterable]
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield ray_tpu.get(ready[0])

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        pass

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
