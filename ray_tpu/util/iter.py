"""ParallelIterator — sharded, lazily-transformed iteration over actors.

Reference analogue: python/ray/util/iter.py (ParallelIterator
:from_items/from_range/from_iterators, for_each, filter, batch, flatten,
gather_sync, gather_async, LocalIterator). Each shard is an actor
holding a generator; transforms compose lazily and execute inside the
shard actor, so `for_each` chains stream without materializing
intermediate lists on the driver.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class _ShardActor:
    """Owns one shard's iterator; applies the transform chain lazily."""

    def __init__(self, make_iter):
        self._make_iter = make_iter
        self._it: Optional[Iterator] = None

    def reset(self, transforms: List[Any]):
        it = iter(self._make_iter())
        for kind, fn in transforms:
            if kind == "for_each":
                it = builtins.map(fn, it)
            elif kind == "filter":
                it = builtins.filter(fn, it)
            elif kind == "batch":
                it = _batched(it, fn)
            elif kind == "flatten":
                it = (x for sub in it for x in sub)
        self._it = it
        return True

    def next_batch(self, n: int) -> List[Any]:
        """Up to n items; empty list = exhausted."""
        assert self._it is not None, "reset() first"
        out = []
        for _ in range(n):
            try:
                out.append(next(self._it))
            except StopIteration:
                break
        return out


def _batched(it: Iterator, size: int) -> Iterator[List[Any]]:
    buf: List[Any] = []
    for x in it:
        buf.append(x)
        if len(buf) >= size:
            yield buf
            buf = []
    if buf:
        yield buf


ShardActor = ray_tpu.remote(_ShardActor)


class ParallelIterator:
    """A set of shard actors + a lazy transform chain."""

    def __init__(self, actors: List[Any], transforms: List[Any]):
        self._actors = actors
        self._transforms = transforms

    # ---- construction ----

    @staticmethod
    def from_iterators(makers: List[Callable[[], Iterable]]
                       ) -> "ParallelIterator":
        return ParallelIterator(
            [ShardActor.remote(m) for m in makers], [])

    @staticmethod
    def from_items(items: List[Any], num_shards: int = 2
                   ) -> "ParallelIterator":
        shards = [items[i::num_shards] for i in range(num_shards)]
        return ParallelIterator.from_iterators(
            [_ListMaker(s) for s in shards])

    @staticmethod
    def from_range(n: int, num_shards: int = 2) -> "ParallelIterator":
        per = [list(range(i, n, num_shards)) for i in range(num_shards)]
        return ParallelIterator.from_iterators(
            [_ListMaker(s) for s in per])

    @property
    def num_shards(self) -> int:
        return len(self._actors)

    # ---- lazy transforms ----

    def _with(self, kind: str, fn) -> "ParallelIterator":
        return ParallelIterator(self._actors,
                                self._transforms + [(kind, fn)])

    def for_each(self, fn: Callable[[Any], Any]) -> "ParallelIterator":
        return self._with("for_each", fn)

    def filter(self, fn: Callable[[Any], bool]) -> "ParallelIterator":
        return self._with("filter", fn)

    def batch(self, size: int) -> "ParallelIterator":
        return self._with("batch", size)

    def flatten(self) -> "ParallelIterator":
        return self._with("flatten", None)

    # ---- gathering ----

    def gather_sync(self, fetch: int = 64) -> Iterator[Any]:
        """Round-robin over shards, in shard order — deterministic."""
        ray_tpu.get([a.reset.remote(self._transforms)
                     for a in self._actors])
        live = list(self._actors)
        while live:
            nxt = []
            for a in live:
                batch = ray_tpu.get(a.next_batch.remote(fetch))
                yield from batch
                if len(batch) == fetch:
                    nxt.append(a)
            live = nxt

    def gather_async(self, fetch: int = 64) -> Iterator[Any]:
        """Items as shards produce them — order across shards is
        whatever finishes first."""
        ray_tpu.get([a.reset.remote(self._transforms)
                     for a in self._actors])
        futs = {a.next_batch.remote(fetch): a for a in self._actors}
        while futs:
            ready, _ = ray_tpu.wait(list(futs), num_returns=1)
            actor = futs.pop(ready[0])
            batch = ray_tpu.get(ready[0])
            yield from batch
            if len(batch) == fetch:
                futs[actor.next_batch.remote(fetch)] = actor
        return

    def take(self, n: int) -> List[Any]:
        out = []
        for x in self.gather_sync():
            out.append(x)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(1 for _ in self.gather_sync())

    def stop(self):
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class _ListMaker:
    """Pickle-friendly shard source (a lambda closing over the list
    would also work, but this names the intent)."""

    def __init__(self, items: List[Any]):
        self._items = items

    def __call__(self) -> Iterable:
        return self._items


def from_items(items, num_shards: int = 2) -> ParallelIterator:
    return ParallelIterator.from_items(items, num_shards)


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return ParallelIterator.from_range(n, num_shards)


def from_iterators(makers) -> ParallelIterator:
    return ParallelIterator.from_iterators(makers)
