"""XLA/TPU profiler integration — jax.profiler traces merged into the
framework timeline by host.

Reference analogue: SURVEY §5.1's TPU mapping of the reference's
profile pipeline (core_worker/profiling.cc + dashboard
reporter/profile_manager.py): keep the chrome-trace timeline, and merge
per-worker `jax.profiler` captures (XLA's own device/compiler spans)
into it so `ray-tpu timeline` shows framework task spans and the XLA
ops they ran, host by host, on one time axis.

Worker-side usage::

    from ray_tpu.util import tpu_profiler
    with tpu_profiler.trace():
        state, metrics = train_step(state, batch)   # jitted work

The capture lands in two places:
  - the raw ``plugins/profile/<run>/`` artifacts (xplane.pb +
    trace.json.gz) under the session dir, for TensorBoard's profile
    plugin;
  - the significant chrome events, rebased to wall-clock and re-tagged
    with this host's identity, recorded into ``ray_tpu.util.timeline``
    — the existing per-process → GCS KV → driver merge carries them
    cross-host exactly like task spans.

``serve(port)`` starts jax's live profiler server for on-demand
TensorBoard attach (the analogue of the dashboard's on-demand py-spy).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import tempfile
import time
import zlib
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from ray_tpu.util import timeline

# chrome pids are ints; XLA process rows get their own block so they
# never collide with framework task pids (os.getpid()-based)
_XLA_PID_BASE = 1 << 24


def load_chrome_events(log_dir: str) -> List[Dict[str, Any]]:
    """Chrome events from every ``*.trace.json.gz`` under a
    jax.profiler log dir (one per host in multi-process captures)."""
    events: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(
            os.path.join(log_dir, "**", "*.trace.json.gz"),
            recursive=True)):
        with gzip.open(path, "rt") as f:
            doc = json.load(f)
        events.extend(doc.get("traceEvents", []))
    return events


def _significant(events: List[Dict[str, Any]], max_events: int,
                 min_dur_us: float) -> List[Dict[str, Any]]:
    """Complete ('X') spans above the duration floor, longest first,
    capped — a raw XLA capture holds far more events than the timeline
    ring buffer should absorb."""
    spans = [e for e in events
             if e.get("ph") == "X" and e.get("dur", 0) >= min_dur_us]
    spans.sort(key=lambda e: -e.get("dur", 0))
    return spans[:max_events]


def merge_into_timeline(events: List[Dict[str, Any]], *,
                        wall_start_us: float, label: str = "xla",
                        max_events: int = 4000,
                        min_dur_us: float = 5.0) -> int:
    """Rebase a capture's events to wall-clock and record them into the
    framework timeline under per-(host,xla-process) rows.  Returns the
    number of events merged."""
    spans = _significant(events, max_events, min_dur_us)
    if not spans:
        return 0
    base = min(e["ts"] for e in spans)
    node = os.environ.get("RTPU_NODE_ID", "")[:8] or "local"
    seen_pids: Dict[int, int] = {}
    for e in spans:
        src_pid = int(e.get("pid", 0))
        pid = seen_pids.get(src_pid)
        if pid is None:
            # Deterministic digest (not Python's randomized hash()) so XLA
            # process rows are stable across restarts and don't collide
            # between hosts within the 16-bit space.
            digest = zlib.crc32(f"{node}:{src_pid}".encode())
            pid = _XLA_PID_BASE + (digest & 0xFFFF)
            seen_pids[src_pid] = pid
            timeline.record(
                "process_name", "M", 0, pid=pid,
                args={"name": f"{label} {node} p{src_pid}"})
        timeline.record(
            e.get("name", "?"), "X",
            wall_start_us + (e["ts"] - base),
            pid=pid, tid=int(e.get("tid", 0)) % 1_000_000,
            dur=e.get("dur", 0), cat=label,
            args=e.get("args") or None)
    timeline.flush()
    return len(spans)


def _capture_dir() -> str:
    root = os.environ.get("RTPU_SESSION_DIR") or tempfile.gettempdir()
    d = os.path.join(root, "xla_profiles")
    os.makedirs(d, exist_ok=True)
    return tempfile.mkdtemp(prefix="capture_", dir=d)


@contextmanager
def trace(label: str = "xla", *, log_dir: Optional[str] = None,
          max_events: int = 4000, min_dur_us: float = 5.0,
          keep_artifacts: bool = True):
    """Capture a jax.profiler trace around the body and merge its
    chrome events into the framework timeline (see module docstring)."""
    import jax

    d = log_dir or _capture_dir()
    wall_start_us = time.time() * 1e6
    jax.profiler.start_trace(d)
    try:
        yield d
    finally:
        jax.profiler.stop_trace()
        try:
            merge_into_timeline(
                load_chrome_events(d), wall_start_us=wall_start_us,
                label=label, max_events=max_events,
                min_dur_us=min_dur_us)
        except Exception:  # a merge failure must not fail the traced op
            pass
        if not keep_artifacts:
            import shutil
            shutil.rmtree(d, ignore_errors=True)


def serve(port: int = 9012):
    """Live profiler server for on-demand TensorBoard capture
    (reference analogue: dashboard reporter's on-demand profiling)."""
    import jax
    return jax.profiler.start_server(port)
