"""Dask-on-ray_tpu scheduler: execute dask task graphs as cluster tasks.

Reference analogue: python/ray/util/dask/scheduler.py (ray_dask_get:83)
— a drop-in `scheduler=` callable for `dask.compute`. A dask graph is a
plain dict {key: computation} where a computation is a literal, a key,
or a task tuple ``(callable, arg...)`` (possibly nested), so the
scheduler needs nothing from dask itself: each graph key becomes one
submitted task whose dependencies are ObjectRefs, giving cluster-wide
parallelism and object-store reuse of intermediates.

With dask installed: ``dask.compute(x, scheduler=ray_dask_get)``.
Without it: call ``ray_dask_get(graph_dict, keys)`` directly.
"""

from __future__ import annotations

from typing import Any, Dict, List

import ray_tpu


def _istask(x: Any) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


def _iskey(x: Any, dsk: Dict) -> bool:
    try:
        return x in dsk
    except TypeError:  # unhashable
        return False


def _find_deps(expr: Any, dsk: Dict, out: set):
    if _istask(expr):
        for a in expr[1:]:
            _find_deps(a, dsk, out)
    elif isinstance(expr, list):
        for a in expr:
            _find_deps(a, dsk, out)
    elif _iskey(expr, dsk):
        out.add(expr)


def _toposort(dsk: Dict) -> List[Any]:
    deps = {}
    for k, expr in dsk.items():
        s: set = set()
        _find_deps(expr, dsk, s)
        s.discard(k)
        deps[k] = s
    order, done, visiting = [], set(), set()

    def visit(k):
        if k in done:
            return
        if k in visiting:
            raise ValueError(f"cycle in dask graph at key {k!r}")
        visiting.add(k)
        for d in deps[k]:
            visit(d)
        visiting.discard(k)
        done.add(k)
        order.append(k)

    for k in dsk:
        visit(k)
    return order


def _eval_expr(expr: Any, env: Dict[Any, Any]) -> Any:
    """Execute a (possibly nested) dask computation inside the task."""
    if _istask(expr):
        fn = expr[0]
        args = [_eval_expr(a, env) for a in expr[1:]]
        return fn(*args)
    if isinstance(expr, list):
        return [_eval_expr(a, env) for a in expr]
    try:
        if expr in env:
            return env[expr]
    except TypeError:
        pass
    return expr


@ray_tpu.remote
def _exec_node(expr, dep_keys, *dep_values):
    return _eval_expr(expr, dict(zip(dep_keys, dep_values)))


def ray_dask_get(dsk: Dict, keys, **_kwargs):
    """Compute `keys` of the graph `dsk`; one cluster task per node.

    Matches dask's scheduler-callable signature, so it plugs into
    ``dask.compute(..., scheduler=ray_dask_get)`` when dask is present.
    """
    refs: Dict[Any, Any] = {}
    for k in _toposort(dsk):
        expr = dsk[k]
        deps: set = set()
        _find_deps(expr, dsk, deps)
        deps.discard(k)
        dep_keys = sorted(deps, key=repr)
        refs[k] = _exec_node.remote(
            expr, dep_keys, *[refs[d] for d in dep_keys])

    def unpack(ks):
        if isinstance(ks, list):
            return [unpack(x) for x in ks]
        return ray_tpu.get(refs[ks])

    return unpack(keys)


def enable_dask_on_ray():
    """Register ray_dask_get as dask's default scheduler (requires
    dask; reference: util/dask/__init__.py enable_dask_on_ray)."""
    try:
        import dask
    except ImportError as e:  # pragma: no cover - dask not in image
        raise ImportError(
            "dask is not installed; call ray_dask_get(graph, keys) "
            "directly on raw graphs instead") from e
    dask.config.set(scheduler=ray_dask_get)
    return ray_dask_get
