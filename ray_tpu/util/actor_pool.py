"""ActorPool — load-balance tasks over a fixed set of actors.

Reference analogue: python/ray/util/actor_pool.py (ActorPool with
submit/get_next/map/map_unordered).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._pending = []  # (fn, value) waiting for a free actor
        self._next_task_index = 0
        self._next_return_index = 0

    def has_free(self) -> bool:
        return len(self._idle) > 0

    def has_next(self) -> bool:
        return bool(self._future_to_actor or self._pending)

    def submit(self, fn: Callable[[Any, Any], Any], value: Any):
        """fn(actor, value) -> ObjectRef; queued until an actor is idle
        (results are never consumed implicitly)."""
        if self._idle:
            self._dispatch(fn, value)
        else:
            self._pending.append((fn, value))

    def _dispatch(self, fn, value):
        actor = self._idle.pop()
        fut = fn(actor, value)
        self._future_to_actor[fut] = actor
        self._index_to_future[self._next_task_index] = fut
        self._next_task_index += 1

    def _free(self, fut):
        self._idle.append(self._future_to_actor.pop(fut))
        if self._pending and self._idle:
            fn, value = self._pending.pop(0)
            self._dispatch(fn, value)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order. On timeout the pool state is
        untouched, so the call can simply be retried."""
        if self._next_return_index >= self._next_task_index:
            raise StopIteration("no pending results")
        from ray_tpu.exceptions import GetTimeoutError
        if self._next_return_index not in self._index_to_future:
            # That index was already consumed by get_next_unordered();
            # mixing the two is undefined ordering (reference ActorPool
            # raises the same guard).
            raise ValueError(
                "get_next() cannot be used after get_next_unordered() "
                "consumed an earlier result; use one mode consistently.")
        fut = self._index_to_future[self._next_return_index]
        try:
            value = ray_tpu.get(fut, timeout=timeout)
        except GetTimeoutError:
            raise  # state untouched: retryable
        except Exception:
            # task failed for real: consume the slot, free the actor
            del self._index_to_future[self._next_return_index]
            self._next_return_index += 1
            self._free(fut)
            raise
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._free(fut)
        return value

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next result in completion order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        fut = ready[0]
        self._free(fut)
        for idx, f in list(self._index_to_future.items()):
            if f == fut:
                del self._index_to_future[idx]
                break
        return ray_tpu.get(fut)

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while (self._next_return_index < self._next_task_index
               or self._pending):
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self._future_to_actor or self._pending:
            yield self.get_next_unordered()
