"""User-facing scheduling strategies.

Reference analogue: python/ray/util/scheduling_strategies.py
(PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy). The
TPU-first addition is SliceSchedulingStrategy: constrain onto hosts of one
TPU slice so gang workers share an ICI domain.
"""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = \
            0 if placement_group_bundle_index < 0 \
            else placement_group_bundle_index
        self.placement_group_capture_child_tasks = \
            placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class SliceSchedulingStrategy:
    """Schedule onto any host of a TPU slice with the given topology
    (e.g. 'v5e-8'); gang members sharing a slice get ICI connectivity."""

    def __init__(self, topology: str, slice_name: Optional[str] = None):
        self.topology = topology
        self.slice_name = slice_name


SPREAD = "SPREAD"
DEFAULT = "DEFAULT"
