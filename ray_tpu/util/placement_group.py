"""Placement group user API.

Reference analogue: python/ray/util/placement_group.py (placement_group():128)
backed by the GCS 2-phase bundle commit (gcs_placement_group_scheduler.cc).
A STRICT_PACK group over TPU bundles lands all bundles on one host; gang
scheduling across a slice uses one bundle per host with SPREAD/STRICT_SPREAD.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.worker import ObjectRef, global_worker
from ray_tpu.common.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, id_hex: str, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id_hex = id_hex
        self.bundles = bundles
        self.strategy = strategy
        self.name = name

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self.bundles)

    def ready(self, timeout: float = 60.0) -> bool:
        w = global_worker()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = w.call_sync(w.gcs, "get_placement_group",
                               {"pg_id": self.id_hex})
            if info.get("state") == "CREATED":
                return True
            if info.get("error"):
                return False
            time.sleep(0.05)
        return False

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"invalid strategy {strategy}")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b}")
    w = global_worker()
    pg_id = PlacementGroupID.of(w.job_id).hex()
    w.call_sync(w.gcs, "create_placement_group", {
        "pg_id": pg_id, "bundles": bundles, "strategy": strategy,
        "name": name, "lifetime": lifetime})
    return PlacementGroup(pg_id, bundles, strategy, name)


def remove_placement_group(pg: PlacementGroup):
    w = global_worker()
    w.call_sync(w.gcs, "remove_placement_group", {"pg_id": pg.id_hex})


def get_placement_group(name: str) -> PlacementGroup:
    w = global_worker()
    pgs = w.call_sync(w.gcs, "list_placement_groups", {})
    for info in pgs:
        if info.get("name") == name:
            return PlacementGroup(info["pg_id"], info["bundles"],
                                  info["strategy"], name)
    raise ValueError(f"no placement group named {name!r}")


def placement_group_table() -> List[Dict]:
    w = global_worker()
    return w.call_sync(w.gcs, "list_placement_groups", {})
