"""Streaming data-plane executor: pipelined block execution with
object-store backpressure.

Reference analogue: python/ray/data/_internal/execution/streaming_executor.py
(StreamingExecutor over PhysicalOperators with per-operator queues and
resource-limited admission). The bulk path (``ExecutionPlan.execute``)
submits every stage across the whole dataset before the consumer sees a
single row; this executor walks the same stage DAG as a pull-based
pipeline instead — blocks flow from the input refs through fused map
tasks to the consumer as soon as each upstream task finishes, and a
bounded in-flight budget (max concurrent tasks AND max buffered bytes,
cross-checked against live plasma usage) provides backpressure so the
object-store footprint stays O(pipeline depth x block size) instead of
O(dataset).

Topology: the stage chain of a plan becomes a linear operator chain

    ReadOp -> [MapOp (fused one-to-one run)] -> [AllToAllOp] -> ...

``MapOp`` streams: one ``_chain_task`` per block, emitted downstream in
submission order as each head-of-line task completes.  ``AllToAllOp``
(shuffle/sort/repartition) is a barrier: it drains its upstream, runs
the stage fn once, then streams the outputs onward — everything after
the barrier still pipelines.

Knobs (read per-run, so tests can flip them):

- ``RTPU_DATA_STREAMING``            "0" disables streaming wholesale
                                     (every consumer falls back to the
                                     bulk path); default on.
- ``RTPU_DATA_MAX_INFLIGHT_TASKS``   max concurrent chain tasks across
                                     the whole pipeline (default 8).
- ``RTPU_DATA_MAX_BUFFERED_BYTES``   max bytes of produced-but-unconsumed
                                     blocks (default 256 MiB).  Until a
                                     task finishes its output size is a
                                     rolling per-op estimate.
- ``RTPU_DATA_STORE_HIGH_WATERMARK`` plasma used/capacity fraction above
                                     which admission pauses (default
                                     0.85).

Per-operator stats (rows, wall, queue depth, backpressure wait) are
recorded into the plan's ``DatasetStats`` so ``Dataset.stats()`` shows
the overlap.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

DEFAULT_MAX_INFLIGHT_TASKS = 8
DEFAULT_MAX_BUFFERED_BYTES = 256 * 1024 * 1024
DEFAULT_STORE_HIGH_WATERMARK = 0.85
# Until an op has seen a completed output, its per-block size estimate.
DEFAULT_EST_BLOCK_BYTES = 64 * 1024
_STORE_POLL_INTERVAL_S = 0.05


def streaming_enabled() -> bool:
    return os.environ.get("RTPU_DATA_STREAMING", "1") != "0"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class StreamingConfig:
    """Backpressure knobs, snapshotted from the environment per run."""

    def __init__(self):
        self.max_inflight_tasks = max(1, _env_int(
            "RTPU_DATA_MAX_INFLIGHT_TASKS", DEFAULT_MAX_INFLIGHT_TASKS))
        self.max_buffered_bytes = max(1, _env_int(
            "RTPU_DATA_MAX_BUFFERED_BYTES", DEFAULT_MAX_BUFFERED_BYTES))
        self.store_high_watermark = _env_float(
            "RTPU_DATA_STORE_HIGH_WATERMARK", DEFAULT_STORE_HIGH_WATERMARK)


class _Budget:
    """Shared admission control: a task occupies a task slot and a byte
    reservation (estimate until completion, actual after) from submission
    until the consumer pulls its output past this op."""

    def __init__(self, cfg: StreamingConfig):
        self.cfg = cfg
        self.inflight_tasks = 0
        self.buffered_bytes = 0
        self.peak_inflight_tasks = 0
        self.peak_buffered_bytes = 0
        self._last_store_poll = 0.0
        self._store_ok = True

    def has_room(self, est_bytes: int) -> bool:
        if self.inflight_tasks >= self.cfg.max_inflight_tasks:
            return False
        if self.buffered_bytes + est_bytes > self.cfg.max_buffered_bytes:
            return False
        return self._store_has_headroom()

    def on_submit(self, est_bytes: int) -> None:
        self.inflight_tasks += 1
        self.buffered_bytes += est_bytes
        self.peak_inflight_tasks = max(self.peak_inflight_tasks,
                                       self.inflight_tasks)
        self.peak_buffered_bytes = max(self.peak_buffered_bytes,
                                       self.buffered_bytes)

    def on_complete(self, est_bytes: int, actual_bytes: int) -> None:
        # swap the reservation from estimate to the real output size
        self.buffered_bytes += actual_bytes - est_bytes
        self.peak_buffered_bytes = max(self.peak_buffered_bytes,
                                       self.buffered_bytes)

    def on_consume(self, actual_bytes: int) -> None:
        self.inflight_tasks -= 1
        self.buffered_bytes -= actual_bytes

    def _store_has_headroom(self) -> bool:
        now = time.monotonic()
        if now - self._last_store_poll < _STORE_POLL_INTERVAL_S:
            return self._store_ok
        self._last_store_poll = now
        try:
            from ray_tpu._private import worker as _worker_mod
            w = _worker_mod._global_worker
            st = w.plasma.stats() if (w is not None and w.plasma) else None
            if st and st.get("capacity"):
                frac = st["used_bytes"] / st["capacity"]
                self._store_ok = frac < self.cfg.store_high_watermark
            else:
                self._store_ok = True
        except Exception:
            self._store_ok = True
        return self._store_ok


class _Op:
    """Base operator: an iterator of (block_ref, bytes_or_None)."""

    name = "op"

    def __iter__(self) -> Iterator[Tuple[Any, Optional[int]]]:
        raise NotImplementedError

    def stats_entry(self) -> Optional[Tuple[str, float, int,
                                            Dict[str, Any]]]:
        return None


class ReadOp(_Op):
    """Source: the plan's input block refs (already materialized or
    produced by eagerly-submitted read tasks)."""

    name = "input"

    def __init__(self, refs: List[Any]):
        self._refs = refs

    def __iter__(self):
        for r in self._refs:
            yield r, None


class MapOp(_Op):
    """A fused run of one-to-one stages, streamed one ``_chain_task`` per
    block.  Emits outputs in submission order (deterministic, identical
    row order to the bulk path); the in-flight window means a slow tail
    block never delays earlier outputs."""

    def __init__(self, name: str, fns: List[Any],
                 remote_opts: Dict[str, Any], upstream: _Op,
                 budget: _Budget):
        self.name = name
        self.fns = fns
        self.remote_opts = {k: v for k, v in remote_opts.items()
                            if k != "_compute"}
        self.upstream = upstream
        self.budget = budget
        # observability
        self.task_stats: List[Dict[str, Any]] = []
        self.queue_depth_max = 0
        self.backpressure_wait_s = 0.0
        self.time_to_first_block_s: Optional[float] = None
        self.wall_s = 0.0
        self.blocks_out = 0
        self._avg_out_bytes: Optional[float] = None

    def _est_bytes(self) -> int:
        if self._avg_out_bytes is not None:
            return int(self._avg_out_bytes)
        return DEFAULT_EST_BLOCK_BYTES

    def __iter__(self):
        import ray_tpu
        from ray_tpu.data._internal.plan import _get_chain_task

        task = _get_chain_task().options(
            **dict(self.remote_opts, num_returns=2))
        pending: deque = deque()  # (out_ref, stats_ref, est_bytes)
        src = iter(self.upstream)
        src_done = False
        blocked = False
        t_start = time.monotonic()
        while True:
            # admission: top up the in-flight window
            blocked = False
            while not src_done:
                est = self._est_bytes()
                if pending and not self.budget.has_room(est):
                    blocked = True
                    break
                try:
                    in_ref, _ = next(src)
                except StopIteration:
                    src_done = True
                    break
                out_ref, stats_ref = task.remote(self.fns, in_ref)
                self.budget.on_submit(est)
                pending.append((out_ref, stats_ref, est))
                self.queue_depth_max = max(self.queue_depth_max,
                                           len(pending))
            if not pending:
                break
            out_ref, stats_ref, est = pending.popleft()
            t0 = time.monotonic()
            ray_tpu.wait([out_ref], num_returns=1, timeout=None)
            waited = time.monotonic() - t0
            if blocked:
                # time spent head-of-line waiting while the budget kept
                # us from submitting more work = observed backpressure
                self.backpressure_wait_s += waited
            try:
                tstats = ray_tpu.get(stats_ref)
            except Exception:
                tstats = None
            actual = int(tstats["bytes_out"]) if tstats else est
            self.budget.on_complete(est, actual)
            if tstats:
                self.task_stats.append(tstats)
                n = len(self.task_stats)
                prev = self._avg_out_bytes or 0.0
                self._avg_out_bytes = prev + (actual - prev) / n
            if self.time_to_first_block_s is None:
                self.time_to_first_block_s = time.monotonic() - t_start
            self.blocks_out += 1
            self.wall_s = time.monotonic() - t_start
            yield out_ref, actual
            # the generator resumed: downstream consumed the block
            self.budget.on_consume(actual)

    def stats_entry(self):
        extra: Dict[str, Any] = {
            "streaming": True,
            "queue_depth_max": self.queue_depth_max,
            "peak_inflight_tasks": self.budget.peak_inflight_tasks,
            "peak_buffered_bytes": self.budget.peak_buffered_bytes,
            "backpressure_wait_s": round(self.backpressure_wait_s, 4),
        }
        if self.time_to_first_block_s is not None:
            extra["time_to_first_block_s"] = round(
                self.time_to_first_block_s, 4)
        rows = self.task_stats
        if rows:
            extra["_task_stats"] = {
                "tasks": len(rows),
                "wall_s": round(sum(r["wall_s"] for r in rows), 4),
                "wall_max_s": round(max(r["wall_s"] for r in rows), 4),
                "cpu_s": round(sum(r["cpu_s"] for r in rows), 4),
                "rows_in": sum(r["rows_in"] for r in rows),
                "rows_out": sum(r["rows_out"] for r in rows),
                "bytes_out": sum(r["bytes_out"] for r in rows),
                "workers": len({r["pid"] for r in rows}),
            }
        return (self.name, self.wall_s, self.blocks_out, extra)


class AllToAllOp(_Op):
    """Barrier operator (shuffle/sort/repartition/limit): drains its
    upstream — which itself streams under the shared budget — then runs
    the stage fn over the full ref list.  Downstream ops resume
    pipelining over the outputs."""

    def __init__(self, name: str, fn: Any, extra: Optional[Dict[str, Any]],
                 upstream: _Op):
        self.name = name
        self.fn = fn
        self.extra = extra
        self.upstream = upstream
        self.wall_s = 0.0
        self.blocks_out = 0

    def __iter__(self):
        t0 = time.monotonic()
        refs = [r for r, _ in self.upstream]
        out = self.fn(refs)
        self.wall_s = time.monotonic() - t0
        self.blocks_out = len(out)
        for r in out:
            yield r, None

    def stats_entry(self):
        extra = dict(self.extra or {})
        extra["streaming"] = True
        return (self.name, self.wall_s, self.blocks_out, extra)


def build_operator_dag(plan, budget: _Budget) -> List[_Op]:
    """Walk the plan's stage chain into a linear operator chain, fusing
    consecutive one-to-one stages exactly like the bulk path does."""
    from ray_tpu.data._internal.plan import AllToAllStage, OneToOneStage

    ops: List[_Op] = [ReadOp(list(plan._in_blocks))]
    stages = list(plan._stages)
    i = 0
    while i < len(stages):
        stage = stages[i]
        if isinstance(stage, OneToOneStage):
            fused = [stage]
            j = i + 1
            while (j < len(stages)
                   and isinstance(stages[j], OneToOneStage)
                   and stages[j].remote_opts == stage.remote_opts):
                fused.append(stages[j])
                j += 1
            ops.append(MapOp("+".join(s.name for s in fused),
                             [s.fn for s in fused], stage.remote_opts,
                             ops[-1], budget))
            i = j
        else:
            assert isinstance(stage, AllToAllStage)
            ops.append(AllToAllOp(stage.name, stage.fn, stage.extra,
                                  ops[-1]))
            i += 1
    return ops


class StreamingExecutor:
    """Runs an ExecutionPlan as a pull-based pipeline.  ``run()`` yields
    (block_ref, bytes_or_None) as each output block becomes available;
    operator stats are recorded into ``plan.stats`` when the stream
    finishes (or is abandoned)."""

    def __init__(self, plan, config: Optional[StreamingConfig] = None):
        self._plan = plan
        self.config = config or StreamingConfig()
        self.budget = _Budget(self.config)
        self._ops = build_operator_dag(plan, self.budget)
        self._recorded = False

    def run(self) -> Iterator[Tuple[Any, Optional[int]]]:
        try:
            for ref, nbytes in self._ops[-1]:
                yield ref, nbytes
        finally:
            self._record_stats()

    def _record_stats(self) -> None:
        if self._recorded:
            return
        self._recorded = True
        for op in self._ops:
            entry = op.stats_entry()
            if entry is None:
                continue
            name, wall_s, blocks, extra = entry
            self._plan.stats.record(name, wall_s, blocks, extra=extra)
