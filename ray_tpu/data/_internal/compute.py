"""Compute strategies for one-to-one stages.

Reference analogue: python/ray/data/_internal/compute.py —
TaskPoolStrategy (default, one task per block) and ActorPoolStrategy:34
(a pool of long-lived actors, the right shape when the map fn has
expensive per-process setup: model weights, a jit-compiled program, a
tokenizer...).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class ComputeStrategy:
    pass


class TaskPoolStrategy(ComputeStrategy):
    def __eq__(self, other):
        return isinstance(other, TaskPoolStrategy)


class ActorPoolStrategy(ComputeStrategy):
    def __init__(self, size: Optional[int] = None,
                 min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        self.size = size or min_size or 2
        self.max_size = max_size or self.size

    def __eq__(self, other):
        # equal strategies let consecutive stages FUSE into one pool run
        return (isinstance(other, ActorPoolStrategy)
                and other.size == self.size
                and other.max_size == self.max_size)

    def __hash__(self):
        return hash((self.size, self.max_size))

    def __repr__(self):
        return (f"ActorPoolStrategy(size={self.size}, "
                f"max_size={self.max_size})")


class _BlockWorker:
    """Pool actor: applies a fused fn chain to blocks. The worker process
    persists across blocks, so per-process setup inside the fns (module
    imports, jit caches) amortizes."""

    def apply(self, fns, block):
        for f in fns:
            block = f(block)
        return block

    def ping(self):
        return "ok"


def resolve_compute(compute) -> ComputeStrategy:
    if compute is None or compute == "tasks":
        return TaskPoolStrategy()
    if compute == "actors":
        return ActorPoolStrategy()
    if isinstance(compute, ComputeStrategy):
        return compute
    raise ValueError(f"bad compute strategy {compute!r}")


def run_on_actor_pool(strategy: ActorPoolStrategy, fns, block_refs,
                      remote_opts: Dict[str, Any]) -> List[Any]:
    """Execute one fused stage over a fresh actor pool. Blocks until the
    stage completes so the pool can be torn down deterministically."""
    import ray_tpu
    n = len(block_refs)
    # grow toward max_size when there are more blocks than min workers
    size = max(strategy.size, min(strategy.max_size, n))
    size = min(size, max(1, n))
    opts = dict(remote_opts)
    worker_cls = (ray_tpu.remote(**opts)(_BlockWorker) if opts
                  else ray_tpu.remote(_BlockWorker))
    pool = [worker_cls.remote() for _ in range(size)]
    try:
        # availability-driven dispatch: the next block goes to whichever
        # worker frees up first, so a straggler block doesn't serialize
        # the blocks statically queued behind its worker
        out: List[Any] = [None] * n
        in_flight: Dict[Any, Any] = {}  # result ref -> worker
        free = list(pool)
        idx = 0
        while idx < n or in_flight:
            while free and idx < n:
                w = free.pop()
                ref = w.apply.remote(fns, block_refs[idx])
                out[idx] = ref
                in_flight[ref] = w
                idx += 1
            if in_flight:
                ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                        timeout=None)
                for r in ready:
                    free.append(in_flight.pop(r))
        return out
    finally:
        for a in pool:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
