"""All-to-all ops: random_shuffle, sort, repartition.

Reference analogue: python/ray/data/_internal/shuffle.py (pull-based
2-stage shuffle) and sort.py (sample boundaries -> range partition ->
merge). Map tasks emit one partition per reducer via ``num_returns=n``;
reduce tasks concatenate their column of partitions — the classic
map/reduce shuffle, with block refs (not bytes) flowing through the
object store.
"""

from __future__ import annotations

import random
from typing import Any, Callable, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, _key_of

_tasks = {}


def _get_tasks():
    if not _tasks:
        import ray_tpu
        _tasks["split_random"] = ray_tpu.remote(_split_random)
        _tasks["split_range"] = ray_tpu.remote(_split_range)
        _tasks["reduce_shuffle"] = ray_tpu.remote(_reduce_shuffle)
        _tasks["reduce_sorted"] = ray_tpu.remote(_reduce_sorted)
        _tasks["slice_block"] = ray_tpu.remote(_slice_block)
        _tasks["concat_blocks"] = ray_tpu.remote(_concat_blocks)
        _tasks["sample_keys"] = ray_tpu.remote(_sample_keys)
    return _tasks


# ------------------------------------------------------------- map side


def _split_random(block: Block, n: int, seed: Optional[int]):
    acc = BlockAccessor.for_block(block)
    rows = acc.num_rows()
    rng = np.random.default_rng(seed)
    assignment = rng.integers(0, n, size=rows)
    parts = []
    for j in range(n):
        idx = np.nonzero(assignment == j)[0].tolist()
        parts.append(acc.select(idx))
    return tuple(parts) if n > 1 else parts[0]


def _split_range(block: Block, boundaries: List[Any], key, descending: bool):
    """Partition rows into len(boundaries)+1 ranges by sort key."""
    acc = BlockAccessor.for_block(block)
    rows = acc.to_pylist()
    n = len(boundaries) + 1
    buckets: List[List[int]] = [[] for _ in range(n)]
    for i, row in enumerate(rows):
        k = _key_of(row, key)
        import bisect
        j = bisect.bisect_right(boundaries, k)
        buckets[j].append(i)
    if descending:
        buckets = buckets[::-1]
    parts = [acc.select(idx) for idx in buckets]
    return tuple(parts) if n > 1 else parts[0]


# ---------------------------------------------------------- reduce side


def _reduce_shuffle(seed: Optional[int], *parts):
    merged = BlockAccessor.concat(list(parts))
    acc = BlockAccessor.for_block(merged)
    rows = acc.num_rows()
    rng = np.random.default_rng(seed)
    perm = rng.permutation(rows).tolist()
    return acc.select(perm)


def _reduce_sorted(key, descending: bool, *parts):
    merged = BlockAccessor.concat(list(parts))
    acc = BlockAccessor.for_block(merged)
    rows = acc.to_pylist()
    order = sorted(range(len(rows)),
                   key=lambda i: _key_of(rows[i], key), reverse=descending)
    return acc.select(order)


def _slice_block(block: Block, start: int, end: int):
    return BlockAccessor.for_block(block).slice(start, end)


def _concat_blocks(*blocks):
    return BlockAccessor.concat(list(blocks))


def _sample_keys(block: Block, key, n: int, seed):
    acc = BlockAccessor.for_block(block)
    vals = acc.sort_key_values(key)
    rng = random.Random(seed)
    if len(vals) > n:
        vals = rng.sample(vals, n)
    return vals


# ------------------------------------------------------------ drivers


def _use_push_based(num_blocks: int) -> bool:
    import os
    env = os.environ.get("RTPU_PUSH_BASED_SHUFFLE")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    # pipelined merge only pays off past a handful of map tasks
    return num_blocks >= 4


def shuffle_blocks(block_refs: List[Any], output_num_blocks: int,
                   seed: Optional[int],
                   stats: Optional[dict] = None) -> List[Any]:
    tasks = _get_tasks()
    n = output_num_blocks
    if not block_refs:
        return []
    split = tasks["split_random"]
    reduce = tasks["reduce_shuffle"]
    if _use_push_based(len(block_refs)):
        from ray_tpu.data._internal.push_based_shuffle import push_shuffle
        # reduce takes (seed, *parts); push hands it ONE merged part
        return push_shuffle(
            block_refs, n, split, reduce,
            map_args=lambda i: (None if seed is None else seed + i,),
            reduce_args=lambda j: (
                None if seed is None else seed + 100003 + j,),
            stats=stats)
    parts = []  # parts[m][j]
    for m, ref in enumerate(block_refs):
        s = None if seed is None else seed + m
        out = split.options(num_returns=n).remote(ref, n, s)
        parts.append(out if isinstance(out, list) else [out])
    outs = []
    for j in range(n):
        s = None if seed is None else seed + 100003 + j
        outs.append(reduce.remote(s, *[parts[m][j]
                                       for m in range(len(parts))]))
    return outs


def sort_blocks(block_refs: List[Any], key, descending: bool) -> List[Any]:
    import ray_tpu
    tasks = _get_tasks()
    if not block_refs:
        return []
    n = len(block_refs)
    # 1. sample boundary keys
    samples = ray_tpu.get([tasks["sample_keys"].remote(r, key, 20, i)
                           for i, r in enumerate(block_refs)])
    allkeys = sorted(k for s in samples for k in s)
    if not allkeys:
        return block_refs
    boundaries = [allkeys[int(len(allkeys) * (j + 1) / n)]
                  for j in range(n - 1)] if n > 1 else []
    # 2. range partition each block
    split = tasks["split_range"]
    parts = []
    for ref in block_refs:
        out = split.options(num_returns=n).remote(
            ref, boundaries, key, descending)
        parts.append(out if isinstance(out, list) else [out])
    # 3. merge-sort each partition column
    reduce = tasks["reduce_sorted"]
    return [reduce.remote(key, descending,
                          *[parts[m][j] for m in range(len(parts))])
            for j in range(n)]


def repartition_blocks(block_refs: List[Any], num_blocks: int,
                       counts: List[int],
                       targets: Optional[List[int]] = None) -> List[Any]:
    """Split/merge into num_blocks blocks without a full shuffle (reference:
    Dataset.repartition(shuffle=False) — splits by row ranges). ``targets``
    optionally pins exact per-output row counts (used by zip alignment)."""
    tasks = _get_tasks()
    total = sum(counts)
    if total == 0:
        return []
    if targets is None:
        targets = [total // num_blocks + (1 if i < total % num_blocks else 0)
                   for i in range(num_blocks)]
    # global row offsets of each input block
    offsets = []
    off = 0
    for c in counts:
        offsets.append((off, off + c))
        off += c
    outs = []
    pos = 0
    for t in targets:
        lo, hi = pos, pos + t
        pieces = []
        for (bs, be), ref in zip(offsets, block_refs):
            s, e = max(lo, bs), min(hi, be)
            if s < e:
                if s == bs and e == be:
                    pieces.append(ref)
                else:
                    pieces.append(tasks["slice_block"].remote(
                        ref, s - bs, e - bs))
        if len(pieces) == 1:
            outs.append(pieces[0])
        else:
            outs.append(tasks["concat_blocks"].remote(*pieces))
        pos = hi
    return outs
