"""Lazy execution plan with stage fusion.

Reference analogue: python/ray/data/_internal/plan.py (ExecutionPlan:74,
execute:288) and compute.py (TaskPoolStrategy). A plan is input block refs
plus a chain of stages; consecutive one-to-one stages fuse into a single
remote task per block (the reference's stage fusion), all-to-all stages
(shuffle/sort/repartition) form barriers.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.data.block import Block, BlockAccessor


class Stage:
    name: str = "stage"


class OneToOneStage(Stage):
    """block -> block, independently per block; fusable."""

    def __init__(self, name: str, fn: Callable[[Block], Block],
                 remote_opts: Optional[Dict[str, Any]] = None):
        self.name = name
        self.fn = fn
        self.remote_opts = remote_opts or {}


class AllToAllStage(Stage):
    """List[ref] -> List[ref] with a barrier (shuffle/sort/repartition).

    ``extra`` is a mutable dict the stage fn may fill with substage
    detail (push-shuffle task counts); it lands in the stats record."""

    def __init__(self, name: str,
                 fn: Callable[[List[Any]], List[Any]],
                 extra: Optional[Dict[str, Any]] = None):
        self.name = name
        self.fn = fn  # (block_refs) -> block_refs
        self.extra = extra


def _apply_chain_with_stats(fns, block):
    """Chain the fns AND return per-task stats as a second return (the
    reference's map tasks do the same — block + BlockMetadata pair) so
    Dataset.stats() can report per-stage wall/cpu/rows without extra
    round trips."""
    import os
    import time as _t
    acc_in = BlockAccessor.for_block(block)
    rows_in = acc_in.num_rows()
    wall0 = _t.perf_counter()
    cpu0 = _t.process_time()
    for f in fns:
        block = f(block)
    acc_out = BlockAccessor.for_block(block)
    return block, {
        "wall_s": _t.perf_counter() - wall0,
        "cpu_s": _t.process_time() - cpu0,
        "rows_in": rows_in,
        "rows_out": acc_out.num_rows(),
        "bytes_out": acc_out.size_bytes(),
        "pid": os.getpid(),
    }


_chain_task = None


def _get_chain_task():
    """Module-level remote task, created lazily so importing ray_tpu.data
    does not require an initialized cluster."""
    global _chain_task
    if _chain_task is None:
        import ray_tpu
        _chain_task = ray_tpu.remote(_apply_chain_with_stats)
    return _chain_task


class DatasetStats:
    """Per-stage wall/cpu/rows breakdowns + substage task detail
    (reference: _internal/stats.py DatasetStats).  Map-stage task stats
    arrive as object refs and resolve lazily at summary time."""

    def __init__(self):
        self.stages: List[Tuple[str, float, int,
                                Optional[Dict[str, Any]]]] = []

    def record(self, name: str, seconds: float, n_blocks: int,
               extra: Optional[Dict[str, Any]] = None,
               task_stats_refs: Optional[List[Any]] = None):
        entry = dict(extra or {})
        if task_stats_refs:
            entry["_task_stats_refs"] = task_stats_refs
        self.stages.append((name, seconds, n_blocks, entry or None))

    def copy(self) -> "DatasetStats":
        out = DatasetStats()
        out.stages = list(self.stages)
        return out

    def _resolve_tasks(self, extra) -> Optional[Dict[str, Any]]:
        if extra and "_task_stats" in extra:
            return extra["_task_stats"]  # resolved once, cached
        refs = (extra or {}).get("_task_stats_refs")
        if not refs:
            return None
        import ray_tpu
        try:
            rows = [r for r in ray_tpu.get(list(refs), timeout=60)
                    if isinstance(r, dict)]
        except Exception:
            rows = []
        if not rows:
            extra["_task_stats"] = None  # don't re-block on lost refs
            extra.pop("_task_stats_refs", None)
            return None
        resolved = {
            "tasks": len(rows),
            "wall_s": round(sum(r["wall_s"] for r in rows), 4),
            "wall_max_s": round(max(r["wall_s"] for r in rows), 4),
            "cpu_s": round(sum(r["cpu_s"] for r in rows), 4),
            "rows_in": sum(r["rows_in"] for r in rows),
            "rows_out": sum(r["rows_out"] for r in rows),
            "bytes_out": sum(r["bytes_out"] for r in rows),
            "workers": len({r["pid"] for r in rows}),
        }
        extra["_task_stats"] = resolved
        extra.pop("_task_stats_refs", None)
        return resolved

    def to_dict(self) -> List[Dict[str, Any]]:
        out = []
        for name, secs, n, extra in self.stages:
            row = {"stage": name, "submit_s": round(secs, 4),
                   "blocks": n}
            tasks = self._resolve_tasks(extra)
            if tasks:
                row.update(tasks)
            if extra:
                row.update({k: v for k, v in extra.items()
                            if not k.startswith("_task_stats")})
            out.append(row)
        return out

    def summary_string(self) -> str:
        lines = ["Dataset stats:"]
        for row in self.to_dict():
            name, n = row["stage"], row["blocks"]
            if "wall_s" in row:
                lines.append(
                    f"  stage {name}: {n} blocks, "
                    f"{row['rows_out']} rows, "
                    f"wall {row['wall_s'] * 1e3:.1f}ms "
                    f"(max {row['wall_max_s'] * 1e3:.1f}ms), "
                    f"cpu {row['cpu_s'] * 1e3:.1f}ms, "
                    f"{row['workers']} workers")
            else:
                lines.append(f"  stage {name}: {n} blocks, "
                             f"{row['submit_s'] * 1e3:.1f}ms")
            detail = ", ".join(
                f"{k}={v}" for k, v in row.items()
                if k not in ("stage", "blocks", "submit_s", "wall_s",
                             "wall_max_s", "cpu_s", "rows_in",
                             "rows_out", "bytes_out", "workers",
                             "tasks"))
            if detail:
                lines.append(f"    {detail}")
        return "\n".join(lines)


class ExecutionPlan:
    def __init__(self, input_blocks: List[Any],
                 stages: Optional[List[Stage]] = None,
                 stats: Optional[DatasetStats] = None):
        self._in_blocks = list(input_blocks)
        self._stages: List[Stage] = list(stages or [])
        self._out_blocks: Optional[List[Any]] = None
        self._out_meta: Optional[List[Any]] = None
        self.stats = stats or DatasetStats()

    def with_stage(self, stage: Stage) -> "ExecutionPlan":
        # the stats history carries over COPIED: sibling datasets branched
        # from one plan must not append into each other's stats
        if self._out_blocks is not None:
            # already executed: new plan starts from materialized blocks
            return ExecutionPlan(self._out_blocks, [stage],
                                 stats=self.stats.copy())
        return ExecutionPlan(self._in_blocks, self._stages + [stage],
                             stats=self.stats.copy())

    def copy_to(self, blocks: List[Any]) -> "ExecutionPlan":
        return ExecutionPlan(blocks)

    def is_executed(self) -> bool:
        return self._out_blocks is not None or not self._stages

    def execute(self) -> List[Any]:
        """Materialize: returns the output block refs."""
        if self._out_blocks is not None:
            return self._out_blocks
        import ray_tpu
        blocks = self._in_blocks
        i = 0
        while i < len(self._stages):
            stage = self._stages[i]
            t0 = time.time()
            if isinstance(stage, OneToOneStage):
                # fuse the run of consecutive one-to-one stages
                fused = [stage]
                j = i + 1
                while (j < len(self._stages)
                       and isinstance(self._stages[j], OneToOneStage)
                       and self._stages[j].remote_opts == stage.remote_opts):
                    fused.append(self._stages[j])
                    j += 1
                fns = [s.fn for s in fused]
                name = "+".join(s.name for s in fused)
                opts = dict(stage.remote_opts)
                compute = opts.pop("_compute", None)
                from ray_tpu.data._internal.compute import (
                    ActorPoolStrategy, run_on_actor_pool)
                if isinstance(compute, ActorPoolStrategy):
                    blocks = run_on_actor_pool(compute, fns, blocks, opts)
                    self.stats.record(name, time.time() - t0, len(blocks))
                else:
                    task = _get_chain_task()
                    opts = dict(opts, num_returns=2)
                    task = task.options(**opts)
                    pairs = [task.remote(fns, b) for b in blocks]
                    blocks = [p[0] for p in pairs]
                    self.stats.record(name, time.time() - t0, len(blocks),
                                      task_stats_refs=[p[1]
                                                       for p in pairs])
                i = j
            else:
                blocks = stage.fn(blocks)
                self.stats.record(stage.name, time.time() - t0,
                                  len(blocks),
                                  extra=getattr(stage, "extra", None))
                i += 1
        # drop references to intermediates; keep outputs pinned
        self._out_blocks = blocks
        self._stages = []
        return blocks

    def supports_streaming(self) -> bool:
        """Whether every stage can run under the streaming executor.
        Actor-pool compute stages manage their own pool lifecycle in the
        bulk helper and keep the bulk path."""
        from ray_tpu.data._internal.compute import ActorPoolStrategy
        for s in self._stages:
            if (isinstance(s, OneToOneStage)
                    and isinstance(s.remote_opts.get("_compute"),
                                   ActorPoolStrategy)):
                return False
        return True

    def execute_streaming(self):
        """Iterator of (block_ref, bytes_or_None), executing pending
        stages as a pull-based pipeline (streaming_executor.py): the
        first output ref is yielded as soon as the first block's fused
        chain completes, with bounded in-flight work behind it.

        Unlike ``execute()`` this does NOT cache outputs: retaining every
        output ref would pin O(dataset) in the object store, defeating
        the bounded-footprint contract.  Consumers that need the
        materialized ref list still call ``execute()``."""
        if self._out_blocks is not None:
            for r in self._out_blocks:
                yield r, None
            return
        if not self._stages:
            for r in self._in_blocks:
                yield r, None
            return
        from ray_tpu.data._internal.streaming_executor import (
            StreamingExecutor)
        yield from StreamingExecutor(self).run()

    def metadata(self) -> List[Any]:
        """BlockMetadata per output block, computed once and cached."""
        if self._out_meta is None:
            self._out_meta = get_metadata(self.execute())
        return self._out_meta


def get_metadata(block_refs: List[Any]) -> List[Any]:
    """Fetch BlockMetadata for each block via small remote tasks."""
    import ray_tpu

    def _meta(block):
        return BlockAccessor.for_block(block).get_metadata()

    meta_task = ray_tpu.remote(_meta)
    return ray_tpu.get([meta_task.remote(b) for b in block_refs])
