"""Lazy execution plan with stage fusion.

Reference analogue: python/ray/data/_internal/plan.py (ExecutionPlan:74,
execute:288) and compute.py (TaskPoolStrategy). A plan is input block refs
plus a chain of stages; consecutive one-to-one stages fuse into a single
remote task per block (the reference's stage fusion), all-to-all stages
(shuffle/sort/repartition) form barriers.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.data.block import Block, BlockAccessor


class Stage:
    name: str = "stage"


class OneToOneStage(Stage):
    """block -> block, independently per block; fusable."""

    def __init__(self, name: str, fn: Callable[[Block], Block],
                 remote_opts: Optional[Dict[str, Any]] = None):
        self.name = name
        self.fn = fn
        self.remote_opts = remote_opts or {}


class AllToAllStage(Stage):
    """List[ref] -> List[ref] with a barrier (shuffle/sort/repartition).

    ``extra`` is a mutable dict the stage fn may fill with substage
    detail (push-shuffle task counts); it lands in the stats record."""

    def __init__(self, name: str,
                 fn: Callable[[List[Any]], List[Any]],
                 extra: Optional[Dict[str, Any]] = None):
        self.name = name
        self.fn = fn  # (block_refs) -> block_refs
        self.extra = extra


def _apply_chain(fns, block):
    for f in fns:
        block = f(block)
    return block


_chain_task = None


def _get_chain_task():
    """Module-level remote task, created lazily so importing ray_tpu.data
    does not require an initialized cluster."""
    global _chain_task
    if _chain_task is None:
        import ray_tpu
        _chain_task = ray_tpu.remote(_apply_chain)
    return _chain_task


class DatasetStats:
    """Per-stage wall time + block counts + substage task breakdowns
    (reference: _internal/stats.py DatasetStats)."""

    def __init__(self):
        self.stages: List[Tuple[str, float, int,
                                Optional[Dict[str, Any]]]] = []

    def record(self, name: str, seconds: float, n_blocks: int,
               extra: Optional[Dict[str, Any]] = None):
        self.stages.append((name, seconds, n_blocks, extra or None))

    def copy(self) -> "DatasetStats":
        out = DatasetStats()
        out.stages = list(self.stages)
        return out

    def summary_string(self) -> str:
        lines = ["Dataset stats:"]
        for name, secs, n, extra in self.stages:
            lines.append(f"  stage {name}: {n} blocks, {secs * 1e3:.1f}ms")
            if extra:
                detail = ", ".join(f"{k}={v}" for k, v in extra.items())
                lines.append(f"    {detail}")
        return "\n".join(lines)


class ExecutionPlan:
    def __init__(self, input_blocks: List[Any],
                 stages: Optional[List[Stage]] = None,
                 stats: Optional[DatasetStats] = None):
        self._in_blocks = list(input_blocks)
        self._stages: List[Stage] = list(stages or [])
        self._out_blocks: Optional[List[Any]] = None
        self._out_meta: Optional[List[Any]] = None
        self.stats = stats or DatasetStats()

    def with_stage(self, stage: Stage) -> "ExecutionPlan":
        # the stats history carries over COPIED: sibling datasets branched
        # from one plan must not append into each other's stats
        if self._out_blocks is not None:
            # already executed: new plan starts from materialized blocks
            return ExecutionPlan(self._out_blocks, [stage],
                                 stats=self.stats.copy())
        return ExecutionPlan(self._in_blocks, self._stages + [stage],
                             stats=self.stats.copy())

    def copy_to(self, blocks: List[Any]) -> "ExecutionPlan":
        return ExecutionPlan(blocks)

    def is_executed(self) -> bool:
        return self._out_blocks is not None or not self._stages

    def execute(self) -> List[Any]:
        """Materialize: returns the output block refs."""
        if self._out_blocks is not None:
            return self._out_blocks
        import ray_tpu
        blocks = self._in_blocks
        i = 0
        while i < len(self._stages):
            stage = self._stages[i]
            t0 = time.time()
            if isinstance(stage, OneToOneStage):
                # fuse the run of consecutive one-to-one stages
                fused = [stage]
                j = i + 1
                while (j < len(self._stages)
                       and isinstance(self._stages[j], OneToOneStage)
                       and self._stages[j].remote_opts == stage.remote_opts):
                    fused.append(self._stages[j])
                    j += 1
                fns = [s.fn for s in fused]
                name = "+".join(s.name for s in fused)
                opts = dict(stage.remote_opts)
                compute = opts.pop("_compute", None)
                from ray_tpu.data._internal.compute import (
                    ActorPoolStrategy, run_on_actor_pool)
                if isinstance(compute, ActorPoolStrategy):
                    blocks = run_on_actor_pool(compute, fns, blocks, opts)
                else:
                    task = _get_chain_task()
                    if opts:
                        task = task.options(**opts)
                    blocks = [task.remote(fns, b) for b in blocks]
                self.stats.record(name, time.time() - t0, len(blocks))
                i = j
            else:
                blocks = stage.fn(blocks)
                self.stats.record(stage.name, time.time() - t0,
                                  len(blocks),
                                  extra=getattr(stage, "extra", None))
                i += 1
        # drop references to intermediates; keep outputs pinned
        self._out_blocks = blocks
        self._stages = []
        return blocks

    def metadata(self) -> List[Any]:
        """BlockMetadata per output block, computed once and cached."""
        if self._out_meta is None:
            self._out_meta = get_metadata(self.execute())
        return self._out_meta


def get_metadata(block_refs: List[Any]) -> List[Any]:
    """Fetch BlockMetadata for each block via small remote tasks."""
    import ray_tpu

    def _meta(block):
        return BlockAccessor.for_block(block).get_metadata()

    meta_task = ray_tpu.remote(_meta)
    return ray_tpu.get([meta_task.remote(b) for b in block_refs])
