"""Push-based (2-stage pipelined) shuffle.

Reference analogue: python/ray/data/_internal/push_based_shuffle.py:23 —
instead of N reduce tasks each waiting on ALL M map outputs (M×N object
pulls at one barrier), map outputs are pushed through intermediate MERGE
tasks in rounds: the merge of round k overlaps the maps of round k+1
(the object-store dependency graph pipelines them), and the final reduce
consumes one merged object per round instead of M partials.

For M maps, R = ceil(M / merge_factor) rounds; per output partition the
merge chain accumulates so at most `merge_factor` map partials are alive
per round — bounding object-store footprint, which is what makes this
the right shape for ImageNet-scale ingest (SURVEY §3.5).
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Optional

from ray_tpu.data.block import BlockAccessor

_tasks = {}


def _get_tasks():
    if not _tasks:
        import ray_tpu
        _tasks["merge"] = ray_tpu.remote(_merge_parts)
    return _tasks


def _merge_parts(*parts):
    parts = [p for p in parts if p is not None]
    if len(parts) == 1:
        return parts[0]
    return BlockAccessor.concat(list(parts))


def push_shuffle(block_refs: List[Any], output_num_blocks: int,
                 map_one: Callable[..., Any],
                 reduce_one: Callable[..., Any],
                 map_args: Callable[[int], tuple],
                 reduce_args: Callable[[int], tuple],
                 merge_factor: int = 4,
                 stats: Optional[dict] = None) -> List[Any]:
    """Generic pipelined shuffle driver.

    map_one(ref, n_out, *map_args(i)) -> n_out partitions (a remote fn
    handle, called with num_returns=n_out; ``map_args`` is a function of
    the global map index so per-map seeds work); reduce_one(
    *reduce_args(j), merged) -> output block j.
    """
    n = output_num_blocks
    m = len(block_refs)
    if m == 0:
        return []
    tasks = _get_tasks()
    merge = tasks["merge"]
    rounds = math.ceil(m / merge_factor)
    # merged[j] = accumulated merge chain for output partition j
    merged: List[Optional[Any]] = [None] * n
    n_merges = 0
    for r in range(rounds):
        lo = r * merge_factor
        chunk = block_refs[lo:lo + merge_factor]
        # this round's map tasks (their partitions are futures; the merge
        # below depends on them and runs as they land, while the NEXT
        # round's maps already execute)
        round_parts = []
        for k, ref in enumerate(chunk):
            out = map_one.options(num_returns=n).remote(
                ref, n, *map_args(lo + k))
            round_parts.append(out if isinstance(out, list) else [out])
        for j in range(n):
            col = [p[j] for p in round_parts]
            if merged[j] is not None:
                col = [merged[j]] + col
            if len(col) == 1:
                merged[j] = col[0]
            else:
                merged[j] = merge.remote(*col)
                n_merges += 1
    if stats is not None:
        stats.update({"map_tasks": m, "merge_tasks": n_merges,
                      "reduce_tasks": n, "rounds": rounds})
    return [reduce_one.remote(*reduce_args(j), merged[j])
            for j in range(n)]
