"""Block layer: the unit of distributed data.

Reference analogue: python/ray/data/block.py + _internal/arrow_block.py /
pandas_block.py / simple_block.py. A Dataset is a list of object refs to
Blocks. A Block is one of:

- a ``pyarrow.Table``            (tabular data — the default for files)
- a ``dict[str, np.ndarray]``    (tensor batch — TPU-first native form; maps
                                  straight to a jit input without conversion)
- a ``list``                     (simple block of arbitrary Python rows)

``BlockAccessor.for_block`` dispatches uniform operations (slice, concat,
format conversion, sampling) over all three. The tensor-dict form is the
TPU-first addition: batches stay as contiguous numpy arrays end-to-end so
``jax.device_put`` is a single zero-copy host→HBM DMA per column.
"""

from __future__ import annotations

import random
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Union

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is baked into the image
    pa = None

Block = Union["pa.Table", Dict[str, np.ndarray], List[Any]]

# Name used when wrapping bare arrays / scalar rows into columnar form
# (reference: ray.data uses "value"/"__value__" for tensor datasets).
VALUE_COL = "value"


@dataclass
class BlockMetadata:
    """Reference analogue: ray.data.block.BlockMetadata."""
    num_rows: int
    size_bytes: int
    schema: Any = None
    input_files: Optional[List[str]] = None


class BlockAccessor:
    """Uniform ops over the three block representations."""

    def __init__(self, block: Block):
        self._block = block

    # ------------------------------------------------------------ dispatch

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        if pa is not None and isinstance(block, pa.Table):
            return ArrowBlockAccessor(block)
        if isinstance(block, dict):
            return TensorBlockAccessor(block)
        if isinstance(block, list):
            return SimpleBlockAccessor(block)
        try:
            import pandas as pd
            if isinstance(block, pd.DataFrame):
                return ArrowBlockAccessor(pa.Table.from_pandas(block))
        except ImportError:
            pass
        raise TypeError(f"not a valid block type: {type(block)}")

    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """Normalize a user-returned batch into a block."""
        if pa is not None and isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        if isinstance(batch, np.ndarray):
            return {VALUE_COL: batch}
        if isinstance(batch, list):
            return batch
        try:
            import pandas as pd
            if isinstance(batch, pd.DataFrame):
                return pa.Table.from_pandas(batch)
        except ImportError:
            pass
        raise TypeError(
            f"map_batches UDF returned {type(batch)}; expected dict of "
            "ndarrays, ndarray, pyarrow.Table, pandas.DataFrame, or list")

    # ----------------------------------------------------------- interface

    def num_rows(self) -> int:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    def schema(self) -> Any:
        raise NotImplementedError

    def slice(self, start: int, end: int) -> Block:
        raise NotImplementedError

    def to_pylist(self) -> List[Any]:
        raise NotImplementedError

    def to_numpy(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def to_arrow(self) -> "pa.Table":
        raise NotImplementedError

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def to_batch(self, batch_format: str) -> Any:
        if batch_format in ("default", "numpy"):
            out = self.to_numpy()
            if set(out.keys()) == {VALUE_COL}:
                return out[VALUE_COL]
            return out
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.to_arrow()
        if batch_format == "pylist":
            return self.to_pylist()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def select(self, indices: List[int]) -> Block:
        raise NotImplementedError

    def get_metadata(self, input_files: Optional[List[str]] = None
                     ) -> BlockMetadata:
        return BlockMetadata(self.num_rows(), self.size_bytes(),
                             self.schema(), input_files)

    def sample_rows(self, n: int, seed: Optional[int] = None) -> List[Any]:
        rows = self.to_pylist()
        rng = random.Random(seed)
        if n >= len(rows):
            return rows
        return rng.sample(rows, n)

    def sort_key_values(self, key) -> List[Any]:
        """Values of the sort key for every row (for boundary sampling)."""
        return [_key_of(r, key) for r in self.to_pylist()]

    # ------------------------------------------------------------- statics

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks
                  if BlockAccessor.for_block(b).num_rows() > 0]
        if not blocks:
            return []
        first = BlockAccessor.for_block(blocks[0])
        if isinstance(first, ArrowBlockAccessor):
            return pa.concat_tables(
                [BlockAccessor.for_block(b).to_arrow() for b in blocks],
                promote_options="permissive")
        if isinstance(first, TensorBlockAccessor):
            keys = list(blocks[0].keys())
            return {k: np.concatenate(
                [np.asarray(b[k]) for b in blocks]) for k in keys}
        out: List[Any] = []
        for b in blocks:
            out.extend(BlockAccessor.for_block(b).to_pylist())
        return out


def _key_of(row: Any, key) -> Any:
    if key is None:
        return row
    if callable(key):
        return key(row)
    if isinstance(row, dict):
        return row[key]
    return getattr(row, key)


def _deep_size(obj: Any, depth: int = 3) -> int:
    """Recursive size estimate: getsizeof is SHALLOW — a dict row of
    512 KiB ndarrays reported ~100 bytes, making size-based splitting
    and spill accounting blind to the real payload."""
    if isinstance(obj, np.ndarray):
        return int(obj.nbytes)
    if depth <= 0:
        return sys.getsizeof(obj)
    if isinstance(obj, dict):
        return sys.getsizeof(obj) + sum(
            _deep_size(v, depth - 1) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        n = len(obj)
        if not n:
            return sys.getsizeof(obj)
        sample = obj[:100]
        est = sum(_deep_size(v, depth - 1) for v in sample)
        return sys.getsizeof(obj) + int(est * (n / len(sample)))
    return sys.getsizeof(obj)


class SimpleBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return len(self._block)

    def size_bytes(self) -> int:
        n = len(self._block)
        if not n:
            return 0
        sample = self._block[:100]
        return int(sum(_deep_size(r) for r in sample) * (n / len(sample)))

    def schema(self) -> Any:
        return type(self._block[0]).__name__ if self._block else None

    def slice(self, start: int, end: int) -> Block:
        return self._block[start:end]

    def to_pylist(self) -> List[Any]:
        return list(self._block)

    def to_numpy(self) -> Dict[str, np.ndarray]:
        if self._block and isinstance(self._block[0], dict):
            keys = self._block[0].keys()
            return {k: np.asarray([r[k] for r in self._block]) for k in keys}
        return {VALUE_COL: np.asarray(self._block)}

    def to_arrow(self) -> "pa.Table":
        if self._block and isinstance(self._block[0], dict):
            return pa.Table.from_pylist(self._block)
        return pa.table({VALUE_COL: self._block})

    def select(self, indices: List[int]) -> Block:
        return [self._block[i] for i in indices]


class TensorBlockAccessor(BlockAccessor):
    def _cols(self) -> Dict[str, np.ndarray]:
        return self._block

    def num_rows(self) -> int:
        if not self._block:
            return 0
        return len(next(iter(self._block.values())))

    def size_bytes(self) -> int:
        return int(sum(np.asarray(v).nbytes for v in self._block.values()))

    def schema(self) -> Any:
        return {k: (np.asarray(v).dtype.str, np.asarray(v).shape[1:])
                for k, v in self._block.items()}

    def slice(self, start: int, end: int) -> Block:
        return {k: np.asarray(v)[start:end] for k, v in self._block.items()}

    def to_pylist(self) -> List[Any]:
        n = self.num_rows()
        keys = list(self._block.keys())
        if keys == [VALUE_COL]:
            return list(self._block[VALUE_COL])
        return [{k: self._block[k][i] for k in keys} for i in range(n)]

    def to_numpy(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self._block.items()}

    def to_arrow(self) -> "pa.Table":
        import json
        arrays, fields = [], []
        for k, v in self._block.items():
            v = np.asarray(v)
            if v.ndim > 1:
                # flatten fixed-shape tensors into FixedSizeList columns;
                # the row shape rides in field metadata so to_numpy can
                # restore ndim>2 tensors losslessly
                flat = v.reshape(len(v), -1)
                arr = pa.FixedSizeListArray.from_arrays(
                    pa.array(flat.ravel()), flat.shape[1])
                fields.append(pa.field(
                    k, arr.type,
                    metadata={b"tensor_shape":
                              json.dumps(v.shape[1:]).encode()}))
                arrays.append(arr)
            else:
                arr = pa.array(v)
                fields.append(pa.field(k, arr.type))
                arrays.append(arr)
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))

    def select(self, indices: List[int]) -> Block:
        idx = np.asarray(indices, dtype=np.int64)
        return {k: np.asarray(v)[idx] for k, v in self._block.items()}


class ArrowBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return self._block.num_rows

    def size_bytes(self) -> int:
        return self._block.nbytes

    def schema(self) -> Any:
        return self._block.schema

    def slice(self, start: int, end: int) -> Block:
        return self._block.slice(start, end - start)

    def to_pylist(self) -> List[Any]:
        return self._block.to_pylist()

    def to_numpy(self) -> Dict[str, np.ndarray]:
        import json
        out = {}
        for name in self._block.column_names:
            col = self._block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                flat = np.asarray(col.combine_chunks().flatten())
                field = self._block.schema.field(name)
                meta = field.metadata or {}
                if b"tensor_shape" in meta:
                    shape = tuple(json.loads(meta[b"tensor_shape"]))
                    out[name] = flat.reshape(
                        (self._block.num_rows,) + shape)
                else:
                    out[name] = flat.reshape(self._block.num_rows, -1)
            else:
                out[name] = np.asarray(col)
        return out

    def to_arrow(self) -> "pa.Table":
        return self._block

    def select(self, indices: List[int]) -> Block:
        return self._block.take(pa.array(indices, type=pa.int64()))
