"""Dataset: distributed data over object-store blocks.

Reference analogue: python/ray/data/dataset.py:139 (Dataset over Blocks,
lazy ExecutionPlan, map/map_batches/filter/flat_map, shuffle/sort/
repartition, split, iter_batches). TPU-first differences:

- the native block form is a dict of contiguous numpy arrays, so a batch
  is already the pytree a jit-compiled step expects;
- ``iter_batches`` pads the last batch (optional) to keep shapes static
  for XLA, and ``iter_device_batches`` double-buffers ``jax.device_put``
  so the host→HBM DMA of batch N+1 overlaps the step on batch N.
"""

from __future__ import annotations

import itertools
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Union)

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata, VALUE_COL
from ray_tpu.data._internal.plan import (AllToAllStage, ExecutionPlan,
                                         OneToOneStage, get_metadata)
from ray_tpu.data._internal import shuffle as _shuffle


class Dataset:
    def __init__(self, plan: ExecutionPlan, epoch: int = 0):
        self._plan = plan
        self._epoch = epoch

    # ----------------------------------------------------------- transforms

    def _one2one(self, name: str, fn: Callable[[Block], Block],
                 **remote_opts) -> "Dataset":
        return Dataset(self._plan.with_stage(
            OneToOneStage(name, fn, remote_opts or None)), self._epoch)

    def map(self, fn: Callable[[Any], Any], **opts) -> "Dataset":
        def _do(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            rows = [fn(r) for r in acc.to_pylist()]
            if rows and isinstance(rows[0], dict) and all(
                    np.isscalar(v) or isinstance(v, np.ndarray)
                    for v in rows[0].values()):
                return BlockAccessor.for_block(rows).to_numpy()
            return rows
        return self._one2one("map", _do, **opts)

    def map_batches(self, fn: Callable[[Any], Any], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "default",
                    compute=None, **opts) -> "Dataset":
        if compute is not None:
            from ray_tpu.data._internal.compute import resolve_compute
            opts["_compute"] = resolve_compute(compute)

        def _do(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            bs = batch_size or max(n, 1)
            outs = []
            for s in range(0, max(n, 1), bs):
                e = min(s + bs, n)
                sub = BlockAccessor.for_block(acc.slice(s, e))
                out = fn(sub.to_batch(batch_format))
                outs.append(BlockAccessor.batch_to_block(out))
            return BlockAccessor.concat(outs)
        return self._one2one("map_batches", _do, **opts)

    def flat_map(self, fn: Callable[[Any], Iterable[Any]], **opts
                 ) -> "Dataset":
        def _do(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            out: List[Any] = []
            for r in acc.to_pylist():
                out.extend(fn(r))
            return out
        return self._one2one("flat_map", _do, **opts)

    def filter(self, fn: Callable[[Any], bool], **opts) -> "Dataset":
        def _do(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            idx = [i for i, r in enumerate(acc.to_pylist()) if fn(r)]
            return acc.select(idx)
        return self._one2one("filter", _do, **opts)

    def add_column(self, name: str, fn: Callable[[Any], np.ndarray],
                   **opts) -> "Dataset":
        def _do(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            cols = acc.to_numpy()
            cols[name] = np.asarray(fn(cols))
            return cols
        return self._one2one("add_column", _do, **opts)

    def drop_columns(self, cols: List[str], **opts) -> "Dataset":
        def _do(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            out = acc.to_numpy()
            return {k: v for k, v in out.items() if k not in cols}
        return self._one2one("drop_columns", _do, **opts)

    def select_columns(self, cols: List[str], **opts) -> "Dataset":
        def _do(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            out = acc.to_numpy()
            return {k: out[k] for k in cols}
        return self._one2one("select_columns", _do, **opts)

    # ----------------------------------------------------------- all-to-all

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        extra: Dict[str, Any] = {}

        def _do(refs):
            n = num_blocks or max(len(refs), 1)
            return _shuffle.shuffle_blocks(refs, n, seed, stats=extra)
        return Dataset(self._plan.with_stage(
            AllToAllStage("random_shuffle", _do, extra=extra)),
            self._epoch)

    def sort(self, key=None, descending: bool = False) -> "Dataset":
        def _do(refs):
            return _shuffle.sort_blocks(refs, key, descending)
        return Dataset(self._plan.with_stage(
            AllToAllStage("sort", _do)), self._epoch)

    def repartition(self, num_blocks: int, *,
                    shuffle: bool = False) -> "Dataset":
        if shuffle:
            def _do(refs):
                return _shuffle.shuffle_blocks(refs, num_blocks, None)
        else:
            def _do(refs):
                counts = [m.num_rows for m in get_metadata(refs)]
                return _shuffle.repartition_blocks(refs, num_blocks, counts)
        return Dataset(self._plan.with_stage(
            AllToAllStage("repartition", _do)), self._epoch)

    def repartition_by_size(self, target_bytes: int) -> "Dataset":
        """Split oversized blocks so none exceeds ``target_bytes`` —
        the block-size-based splitting the reference applies dynamically
        in its map tasks (reference: _internal/plan -> block splitting
        on target_max_block_size).  Splits run remotely per block; no
        driver materialization."""
        if target_bytes <= 0:
            raise ValueError("target_bytes must be positive")

        def _do(refs):
            import ray_tpu
            metas = get_metadata(refs)

            def _split(block, parts):
                acc = BlockAccessor.for_block(block)
                n = acc.num_rows()
                cuts = [round(i * n / parts) for i in range(parts + 1)]
                return [acc.slice(cuts[i], cuts[i + 1])
                        for i in range(parts)]

            split_task = ray_tpu.remote(_split)  # ONE export for all blocks
            out = []
            for ref, m in zip(refs, metas):
                parts = -(-max(m.size_bytes, 1) // target_bytes)
                if parts <= 1 or m.num_rows <= 1:
                    out.append(ref)
                    continue
                parts = min(parts, m.num_rows)
                pieces = split_task.options(
                    num_returns=parts).remote(ref, parts)
                out.extend(pieces if isinstance(pieces, list)
                           else [pieces])
            return out

        return Dataset(self._plan.with_stage(
            AllToAllStage("repartition_by_size", _do)), self._epoch)

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "Dataset":
        def _do(refs):
            import random as _r
            rng = _r.Random(seed)
            refs = list(refs)
            rng.shuffle(refs)
            return refs
        return Dataset(self._plan.with_stage(
            AllToAllStage("randomize_block_order", _do)), self._epoch)

    def limit(self, n: int) -> "Dataset":
        def _do(refs):
            counts = [m.num_rows for m in get_metadata(refs)]
            tasks = _shuffle._get_tasks()
            out, used = [], 0
            for ref, c in zip(refs, counts):
                if used >= n:
                    break
                take = min(c, n - used)
                out.append(ref if take == c else
                           tasks["slice_block"].remote(ref, 0, take))
                used += take
            return out
        return Dataset(self._plan.with_stage(
            AllToAllStage("limit", _do)), self._epoch)

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = list(self._blocks())
        for o in others:
            blocks.extend(o._blocks())
        return Dataset(ExecutionPlan(blocks), self._epoch)

    def zip(self, other: "Dataset") -> "Dataset":
        """Zip columns of two datasets row-aligned (requires equal counts)."""
        import ray_tpu
        left = self._blocks()
        right = other._blocks()
        lc = [m.num_rows for m in self._meta()]
        rc = [m.num_rows for m in other._meta()]
        if sum(lc) != sum(rc):
            raise ValueError("zip requires equal row counts")
        if lc != rc:
            right = _shuffle.repartition_blocks(right, len(lc), rc,
                                                targets=lc)

        def _zip(a, b):
            ca = BlockAccessor.for_block(a).to_numpy()
            cb = BlockAccessor.for_block(b).to_numpy()
            out = dict(ca)
            for k, v in cb.items():
                out[k if k not in out else k + "_1"] = v
            return out
        zt = ray_tpu.remote(_zip)
        return Dataset(ExecutionPlan(
            [zt.remote(a, b) for a, b in zip(left, right)]), self._epoch)

    # ------------------------------------------------------------ splitting

    def split(self, n: int, *, equal: bool = True,
              locality_hints=None) -> List["Dataset"]:
        """Split into n datasets with equal row counts (reference:
        dataset.py split; used by Train to shard per worker)."""
        refs = self._blocks()
        counts = [m.num_rows for m in self._meta()]
        total = sum(counts)
        per = total // n if equal else None
        outs = []
        for i in range(n):
            lo = i * per if equal else (total * i) // n
            hi = (i + 1) * per if equal else (total * (i + 1)) // n
            outs.append((lo, hi))
        return self._split_ranges(refs, counts, outs)

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        refs = self._blocks()
        counts = [m.num_rows for m in self._meta()]
        total = sum(counts)
        bounds = [0] + list(indices) + [total]
        ranges = [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]
        return self._split_ranges(refs, counts, ranges)

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        total = ds.count()
        n_test = int(total * test_size) if isinstance(test_size, float) \
            else int(test_size)
        train, test = ds.split_at_indices([total - n_test])
        return train, test

    def streaming_split(self, n: int) -> List["Dataset"]:
        """Split into n shards WITHOUT materializing pending one-to-one
        stages: input blocks are partitioned round-robin and every shard
        carries the un-executed stage chain, so each consumer (e.g. a
        train worker) streams its own shard through the pipeline.  Shards
        are block-aligned, not row-equal — use ``split`` when exact row
        balance matters.  Falls back to ``split`` when the plan is
        already executed, has non-one-to-one stages, or has fewer input
        blocks than shards."""
        from ray_tpu.data._internal.plan import OneToOneStage
        plan = self._plan
        if (plan.is_executed()
                or not all(isinstance(s, OneToOneStage)
                           for s in plan._stages)
                or len(plan._in_blocks) < n):
            return self.split(n)
        return [Dataset(ExecutionPlan(plan._in_blocks[i::n],
                                      list(plan._stages),
                                      stats=plan.stats.copy()),
                        self._epoch)
                for i in range(n)]

    def _split_ranges(self, refs, counts, ranges) -> List["Dataset"]:
        tasks = _shuffle._get_tasks()
        offsets = []
        off = 0
        for c in counts:
            offsets.append((off, off + c))
            off += c
        outs = []
        for lo, hi in ranges:
            pieces = []
            for (bs, be), ref in zip(offsets, refs):
                s, e = max(lo, bs), min(hi, be)
                if s < e:
                    pieces.append(ref if (s == bs and e == be) else
                                  tasks["slice_block"].remote(
                                      ref, s - bs, e - bs))
            outs.append(Dataset(ExecutionPlan(pieces), self._epoch))
        return outs

    # ---------------------------------------------------------- aggregates

    def count(self) -> int:
        return sum(m.num_rows for m in self._meta())

    def size_bytes(self) -> int:
        return sum(m.size_bytes for m in self._meta())

    def num_blocks(self) -> int:
        return len(self._blocks())

    def schema(self):
        refs = self._blocks()
        if not refs:
            return None
        return get_metadata(refs[:1])[0].schema

    def _agg(self, on: Optional[str], np_fn, combine):
        import ray_tpu

        def _block_agg(block):
            acc = BlockAccessor.for_block(block)
            if acc.num_rows() == 0:
                return None
            cols = acc.to_numpy()
            col = cols[on] if on else cols[VALUE_COL]
            return np_fn(np.asarray(col))
        t = ray_tpu.remote(_block_agg)
        vals = [v for v in ray_tpu.get(
            [t.remote(b) for b in self._blocks()]) if v is not None]
        if not vals:
            return None
        return combine(vals)

    def sum(self, on: Optional[str] = None):
        return self._agg(on, np.sum, sum)

    def min(self, on: Optional[str] = None):
        return self._agg(on, np.min, min)

    def max(self, on: Optional[str] = None):
        return self._agg(on, np.max, max)

    def mean(self, on: Optional[str] = None):
        s = self._agg(on, np.sum, sum)
        c = self.count()
        return None if not c else s / c

    def std(self, on: Optional[str] = None, ddof: int = 1):
        import math
        c = self.count()
        if not c:
            return None
        s = self._agg(on, np.sum, sum)
        ss = self._agg(on, lambda a: np.sum(a.astype(np.float64) ** 2), sum)
        mean = s / c
        var = (ss - c * mean * mean) / max(c - ddof, 1)
        return math.sqrt(max(var, 0.0))

    def groupby(self, key):
        from ray_tpu.data.grouped_data import GroupedData
        return GroupedData(self, key)

    # ----------------------------------------------------------- consuming

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        import ray_tpu
        out: List[Any] = []
        for b in ray_tpu.get(self._blocks()):
            out.extend(BlockAccessor.for_block(b).to_pylist())
        return out

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_blocks():
            yield from BlockAccessor.for_block(block).to_pylist()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "default",
                     drop_last: bool = False,
                     pad_to_batch: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_blocks: int = 1) -> Iterator[Any]:
        """Iterate fixed-size batches. ``pad_to_batch`` repeats final rows so
        every batch has identical shape — keeps XLA from recompiling on the
        remainder batch (TPU-first; no reference analogue). ``pad_to_batch``
        wins over ``drop_last``: a padded remainder is always emitted.
        ``prefetch_blocks`` block pulls run ahead on a background thread so
        object-store fetches overlap consumption.

        Under the streaming executor (RTPU_DATA_STREAMING, default on)
        pending stages execute as a pull-based pipeline: the first batch
        yields after the FIRST block's chain completes rather than the
        last, with a bounded in-flight budget behind it."""
        shuffler = _LocalShuffler(local_shuffle_buffer_size,
                                  local_shuffle_seed)
        carry: Optional[Block] = None
        for block in self._iter_blocks(prefetch_blocks):
            block = shuffler.feed(block)
            if block is None:
                continue
            if carry is not None:
                block = BlockAccessor.concat([carry, block])
                carry = None
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            s = 0
            while n - s >= batch_size:
                yield BlockAccessor.for_block(
                    acc.slice(s, s + batch_size)).to_batch(batch_format)
                s += batch_size
            if s < n:
                carry = acc.slice(s, n)
        tail = shuffler.drain()
        if tail is not None:
            carry = tail if carry is None else BlockAccessor.concat(
                [carry, tail])
        if carry is not None:
            acc = BlockAccessor.for_block(carry)
            n = acc.num_rows()
            s = 0
            while n - s >= batch_size:
                yield BlockAccessor.for_block(
                    acc.slice(s, s + batch_size)).to_batch(batch_format)
                s += batch_size
            rem = n - s
            if rem:
                last = acc.slice(s, n)
                if pad_to_batch:
                    la = BlockAccessor.for_block(last)
                    need = batch_size - rem
                    idx = (list(range(rem)) * (need // rem + 1))[:need]
                    last = BlockAccessor.concat([last, la.select(idx)])
                    yield BlockAccessor.for_block(last).to_batch(
                        batch_format)
                elif not drop_last:
                    yield BlockAccessor.for_block(last).to_batch(
                        batch_format)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None, drop_last: bool = False,
                           local_shuffle_buffer_size: Optional[int] = None,
                           local_shuffle_seed: Optional[int] = None,
                           prefetch_blocks: int = 1) -> Iterator[Any]:
        """iter_batches with columns converted to torch tensors
        (reference: Dataset.iter_torch_batches). ``dtypes`` maps column
        name -> torch dtype (or one dtype for all)."""
        import torch

        def _to_torch(col, name):
            t = torch.as_tensor(np.ascontiguousarray(col))
            if dtypes is None:
                return t
            want = dtypes.get(name) if isinstance(dtypes, dict) else dtypes
            return t.to(want) if want is not None else t

        for batch in self.iter_batches(
                batch_size=batch_size, batch_format="numpy",
                drop_last=drop_last,
                local_shuffle_buffer_size=local_shuffle_buffer_size,
                local_shuffle_seed=local_shuffle_seed,
                prefetch_blocks=prefetch_blocks):
            if isinstance(batch, dict):
                yield {k: _to_torch(v, k) for k, v in batch.items()}
            else:
                yield _to_torch(batch, VALUE_COL)

    def iter_device_batches(self, *, batch_size: int = 256,
                            sharding=None, dtypes=None,
                            drop_last: bool = False,
                            pad_to_batch: bool = True,
                            **kw) -> Iterator[Any]:
        """Batches as committed jax.Arrays with 1-deep device prefetch:
        device_put of batch N+1 is issued before batch N is yielded, so the
        host→HBM DMA overlaps the consumer's step (TPU-first; reference
        analogue in spirit: iter_torch_batches with prefetch)."""
        import jax

        def _put(batch):
            if dtypes:
                if isinstance(batch, dict):
                    batch = {k: np.asarray(v).astype(dtypes.get(k, v.dtype))
                             for k, v in batch.items()}
                else:
                    batch = np.asarray(batch).astype(dtypes)
            return (jax.device_put(batch, sharding) if sharding is not None
                    else jax.device_put(batch))

        it = self.iter_batches(batch_size=batch_size, batch_format="numpy",
                               drop_last=drop_last,
                               pad_to_batch=pad_to_batch, **kw)
        prev = None
        for batch in it:
            cur = _put(batch)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev

    def iter_tf_batches(self, *, batch_size: int = 256,
                        dtypes=None, drop_last: bool = False,
                        prefetch_blocks: int = 1, **kw) -> Iterator[Any]:
        """iter_batches with columns converted to tf tensors
        (reference: Dataset.iter_tf_batches)."""
        import tensorflow as tf

        def _to_tf(col, name):
            want = (dtypes.get(name) if isinstance(dtypes, dict)
                    else dtypes)
            return tf.convert_to_tensor(np.ascontiguousarray(col),
                                        dtype=want)

        for batch in self.iter_batches(
                batch_size=batch_size, batch_format="numpy",
                drop_last=drop_last, prefetch_blocks=prefetch_blocks,
                **kw):
            if isinstance(batch, dict):
                yield {k: _to_tf(v, k) for k, v in batch.items()}
            else:
                yield _to_tf(batch, VALUE_COL)

    def to_tf(self, *, feature_columns, label_columns=None,
              batch_size: int = 256,
              drop_last: bool = False) -> Any:
        """A ``tf.data.Dataset`` over this dataset's batches
        (reference: Dataset.to_tf — feature/label column split, batched).
        ``feature_columns``/``label_columns`` may be one name or a list;
        a list yields a dict of tensors per element."""
        import tensorflow as tf

        first = self.take(1)
        if not first:
            raise ValueError("to_tf on an empty dataset: the element "
                             "spec cannot be inferred from zero rows")
        one = first[0]

        def _spec(cols):
            if cols is None:
                return None

            def spec_for(name):
                v = np.asarray(one[name])
                return tf.TensorSpec(shape=(None,) + v.shape,
                                     dtype=tf.as_dtype(v.dtype))
            if isinstance(cols, str):
                return spec_for(cols)
            return {c: spec_for(c) for c in cols}

        feat_spec = _spec(feature_columns)
        label_spec = _spec(label_columns)

        def _select(batch, cols):
            if isinstance(cols, str):
                return tf.convert_to_tensor(batch[cols])
            return {c: tf.convert_to_tensor(batch[c]) for c in cols}

        def gen():
            for batch in self.iter_batches(batch_size=batch_size,
                                           batch_format="numpy",
                                           drop_last=drop_last):
                feats = _select(batch, feature_columns)
                if label_columns is None:
                    yield feats
                else:
                    yield feats, _select(batch, label_columns)

        sig = feat_spec if label_spec is None else (feat_spec, label_spec)
        return tf.data.Dataset.from_generator(gen, output_signature=sig)

    def to_pandas(self):
        import pandas as pd
        import ray_tpu
        blocks = ray_tpu.get(self._blocks())
        return pd.concat(
            [BlockAccessor.for_block(b).to_pandas() for b in blocks],
            ignore_index=True)

    def to_arrow(self):
        import ray_tpu
        import pyarrow as pa
        blocks = ray_tpu.get(self._blocks())
        return pa.concat_tables(
            [BlockAccessor.for_block(b).to_arrow() for b in blocks])

    def to_numpy(self) -> Dict[str, np.ndarray]:
        import ray_tpu
        blocks = ray_tpu.get(self._blocks())
        return BlockAccessor.for_block(
            BlockAccessor.concat(blocks)).to_numpy()

    # -------------------------------------------------------------- writing

    def write_parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def write_csv(self, path: str) -> None:
        self._write(path, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, "json")

    def write_numpy(self, path: str, column: str = VALUE_COL) -> None:
        import os
        import ray_tpu
        os.makedirs(path, exist_ok=True)

        def _w(block, p, col):
            cols = BlockAccessor.for_block(block).to_numpy()
            np.save(p, cols[col])
            return p
        t = ray_tpu.remote(_w)
        refs = [t.remote(b, os.path.join(path, f"{i:06}.npy"), column)
                for i, b in enumerate(self._blocks())]
        ray_tpu.get(refs)

    def _write(self, path: str, fmt: str) -> None:
        import os
        import ray_tpu
        os.makedirs(path, exist_ok=True)

        def _w(block, p, f):
            table = BlockAccessor.for_block(block).to_arrow()
            if f == "parquet":
                import pyarrow.parquet as pq
                pq.write_table(table, p)
            elif f == "csv":
                import pyarrow.csv as pcsv
                pcsv.write_csv(table, p)
            else:
                table.to_pandas().to_json(p, orient="records", lines=True)
            return p
        t = ray_tpu.remote(_w)
        ext = {"parquet": "parquet", "csv": "csv", "json": "json"}[fmt]
        refs = [t.remote(b, os.path.join(path, f"{i:06}.{ext}"), fmt)
                for i, b in enumerate(self._blocks())]
        ray_tpu.get(refs)

    # ------------------------------------------------------------ pipelines

    def repeat(self, times: Optional[int] = None):
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline.from_dataset_repeat(self, times)

    def window(self, *, blocks_per_window: int = 10):
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline.from_dataset_windows(self, blocks_per_window)

    # ------------------------------------------------------------- plumbing

    def materialize(self) -> "Dataset":
        self._blocks()
        return self

    fully_executed = materialize

    def stats(self) -> str:
        return self._plan.stats.summary_string()

    def _blocks(self) -> List[Any]:
        return self._plan.execute()

    def _iter_blocks(self, prefetch_blocks: int = 1) -> Iterator[Block]:
        """Yield block VALUES in order.  With the streaming executor
        enabled and pending stages, blocks are produced by the pull-based
        pipeline (O(depth) in-flight, first block available after one
        chain); otherwise the plan bulk-materializes and blocks are
        fetched with thread prefetch."""
        from ray_tpu.data._internal.streaming_executor import (
            streaming_enabled)
        plan = self._plan
        if (streaming_enabled() and not plan.is_executed()
                and plan.supports_streaming()):
            import ray_tpu
            for ref, _ in plan.execute_streaming():
                yield ray_tpu.get(ref)
            return
        yield from _iter_blocks_prefetch(self._blocks(), prefetch_blocks)

    def _meta(self) -> List[BlockMetadata]:
        return self._plan.metadata()

    def __repr__(self) -> str:
        if self._plan.is_executed():
            return (f"Dataset(num_blocks={self.num_blocks()}, "
                    f"num_rows={self.count()}, schema={self.schema()})")
        return "Dataset(lazy)"


def _iter_blocks_prefetch(refs: List[Any], depth: int) -> Iterator[Block]:
    """Yield blocks with up to ``depth`` pulls running ahead on a background
    thread, so object-store fetch of block N+1 overlaps consumption of N.

    Generator close (an abandoned ``iter_batches`` iterator) must not leak
    the thread: the finally clause signals stop, drains the queue so a
    blocked ``put`` wakes, and joins the thread with a bounded timeout.
    The thread stays daemonized so a ``get`` stuck on a lost object can
    never pin process exit."""
    import ray_tpu
    if depth <= 0 or len(refs) <= 1:
        for r in refs:
            yield ray_tpu.get(r)
        return
    import queue as _q
    import threading
    q: "_q.Queue" = _q.Queue(maxsize=depth)
    sentinel = object()
    stop = threading.Event()
    err: List[BaseException] = []

    def _pull():
        try:
            for r in refs:
                if stop.is_set():
                    return
                b = ray_tpu.get(r)
                while not stop.is_set():
                    try:
                        q.put(b, timeout=0.1)
                        break
                    except _q.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:
            err.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    break
                except _q.Full:
                    continue

    t = threading.Thread(target=_pull, daemon=True,
                         name="rtpu-data-prefetch")
    t.start()
    try:
        while True:
            b = q.get()
            if b is sentinel:
                break
            yield b
        if err:
            raise err[0]
    finally:
        stop.set()
        try:  # unblock a producer stuck in q.put
            while True:
                q.get_nowait()
        except _q.Empty:
            pass
        t.join(timeout=5.0)


class _LocalShuffler:
    """Buffered local shuffle for iter_batches (reference:
    local_shuffle_buffer_size semantics)."""

    def __init__(self, buffer_size: Optional[int], seed: Optional[int]):
        self.size = buffer_size
        self.rng = np.random.default_rng(seed)
        self.buf: List[Block] = []
        self.rows = 0

    def feed(self, block: Block) -> Optional[Block]:
        if not self.size:
            return block
        self.buf.append(block)
        self.rows += BlockAccessor.for_block(block).num_rows()
        if self.rows >= self.size * 2:
            return self._emit(self.size)
        return None

    def drain(self) -> Optional[Block]:
        if not self.size or not self.buf:
            return None
        return self._emit(0)

    def _emit(self, keep: int) -> Block:
        merged = BlockAccessor.concat(self.buf)
        acc = BlockAccessor.for_block(merged)
        n = acc.num_rows()
        perm = self.rng.permutation(n)
        out_n = n - keep
        out = acc.select(perm[:out_n].tolist())
        rest = acc.select(perm[out_n:].tolist())
        self.buf = [rest] if keep else []
        self.rows = keep
        return out
