"""ray_tpu.data — distributed datasets over the object store.

Reference analogue: python/ray/data (Dataset over blocks, read API,
transforms, shuffle, split, batch iteration). TPU-first: tensor-dict
blocks, static-shape batch padding, jax.device_put prefetch iterators.

Iteration runs on the streaming executor by default (RTPU_DATA_STREAMING,
see _internal/streaming_executor.py): pending stages execute as a
pull-based pipeline with object-store backpressure, so the first batch
yields after the first block chain completes and the in-flight footprint
stays bounded.  ``materialize()`` and the all-to-all barriers keep the
bulk path; RTPU_DATA_STREAMING=0 falls back to it wholesale.
"""

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data._internal.compute import (ActorPoolStrategy,
                                            TaskPoolStrategy)
from ray_tpu.data._internal.streaming_executor import (StreamingConfig,
                                                       streaming_enabled)
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.dataset_pipeline import DatasetPipeline
from ray_tpu.data.grouped_data import GroupedData
from ray_tpu.data.read_api import (Datasource, from_arrow, from_items,
                                   from_numpy, from_pandas, range,
                                   range_tensor, read_binary_files, read_csv,
                                   read_datasource, read_images, read_json,
                                   read_mongo, read_numpy, read_parquet,
                                   read_text)

__all__ = [
    "Dataset", "DatasetPipeline", "GroupedData", "Block", "BlockAccessor",
    "BlockMetadata", "Datasource", "range", "range_tensor", "from_items",
    "from_numpy", "from_pandas", "from_arrow", "read_parquet", "read_csv",
    "read_json", "read_numpy", "read_text", "read_binary_files",
    "read_images", "read_mongo",
    "read_datasource", "ActorPoolStrategy", "TaskPoolStrategy",
    "StreamingConfig", "streaming_enabled",
]
