"""GroupedData: groupby/aggregate over a sorted range partition.

Reference analogue: python/ray/data/grouped_dataset.py. Strategy: sort by
the group key (range-partitions co-locate equal keys in one block), then
aggregate group runs per block — no cross-block groups by construction.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import BlockAccessor, _key_of


class GroupedData:
    def __init__(self, dataset, key):
        self._ds = dataset
        self._key = key

    def _grouped_blocks(self):
        return self._ds.sort(self._key)

    def map_groups(self, fn: Callable[[Any], Any], *,
                   batch_format: str = "pylist"):
        """Apply fn to each group; returns a new Dataset."""
        key = self._key

        def _do(block):
            acc = BlockAccessor.for_block(block)
            rows = acc.to_pylist()
            out: List[Any] = []
            start = 0
            for i in range(1, len(rows) + 1):
                if i == len(rows) or _key_of(rows[i], key) != _key_of(
                        rows[start], key):
                    group = BlockAccessor.for_block(
                        acc.slice(start, i)).to_batch(batch_format)
                    res = fn(group)
                    if isinstance(res, list):
                        out.extend(res)
                    else:
                        out.append(res)
                    start = i
            return out
        return self._grouped_blocks()._one2one("map_groups", _do)

    def _agg(self, np_fn, name: str, on: Optional[str]):
        key = self._key

        def _do(group_rows):
            k = _key_of(group_rows[0], key)
            if on is not None:
                vals = np.asarray([r[on] for r in group_rows])
            else:
                vals = np.asarray(
                    [r for r in group_rows]) if not isinstance(
                        group_rows[0], dict) else np.asarray(
                        [[v for kk, v in sorted(r.items()) if kk != key]
                         for r in group_rows])
            col = on or name
            return {key if isinstance(key, str) else "key": k,
                    f"{name}({col})" if on else name: np_fn(vals)}
        return self.map_groups(_do)

    def count(self):
        key = self._key

        def _do(rows):
            return {key if isinstance(key, str) else "key":
                    _key_of(rows[0], key), "count()": len(rows)}
        return self.map_groups(_do)

    def sum(self, on: Optional[str] = None):
        return self._agg(np.sum, "sum", on)

    def min(self, on: Optional[str] = None):
        return self._agg(np.min, "min", on)

    def max(self, on: Optional[str] = None):
        return self._agg(np.max, "max", on)

    def mean(self, on: Optional[str] = None):
        return self._agg(np.mean, "mean", on)

    def std(self, on: Optional[str] = None):
        return self._agg(lambda a: float(np.std(a, ddof=1)) if len(a) > 1
                         else 0.0, "std", on)
