"""DatasetPipeline: windowed/repeated streaming over datasets.

Reference analogue: python/ray/data/dataset_pipeline.py (windowed streaming
of block sets so transform of window N overlaps consumption of N-1; here
windows execute lazily on first touch which gives the same pipelining
through the object store's async task graph).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional


class DatasetPipeline:
    def __init__(self, stages_fn: Callable[[], Iterator["Any"]]):
        self._gen_windows = stages_fn
        self._xforms: List[Callable[[Any], Any]] = []

    # ----------------------------------------------------------- factories

    @staticmethod
    def from_dataset_repeat(ds, times: Optional[int]) -> "DatasetPipeline":
        def gen():
            i = 0
            while times is None or i < times:
                yield ds
                i += 1
        return DatasetPipeline(gen)

    @staticmethod
    def from_dataset_windows(ds, blocks_per_window: int) -> "DatasetPipeline":
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data._internal.plan import ExecutionPlan, OneToOneStage

        def gen():
            plan = ds._plan
            if (not plan.is_executed()
                    and all(isinstance(s, OneToOneStage)
                            for s in plan._stages)):
                # carry un-executed one-to-one stages into each window's
                # plan instead of bulk-executing the whole dataset up
                # front — a window then streams its own chain
                refs, stages = plan._in_blocks, list(plan._stages)
            else:
                refs, stages = ds._blocks(), []
            for s in range(0, len(refs), blocks_per_window):
                yield Dataset(ExecutionPlan(refs[s:s + blocks_per_window],
                                            list(stages)))
        return DatasetPipeline(gen)

    # ---------------------------------------------------------- transforms

    def _chain(self, f: Callable[[Any], Any]) -> "DatasetPipeline":
        p = DatasetPipeline(self._gen_windows)
        p._xforms = self._xforms + [f]
        return p

    def map(self, fn, **kw):
        return self._chain(lambda ds: ds.map(fn, **kw))

    def map_batches(self, fn, **kw):
        return self._chain(lambda ds: ds.map_batches(fn, **kw))

    def filter(self, fn, **kw):
        return self._chain(lambda ds: ds.filter(fn, **kw))

    def random_shuffle_each_window(self, **kw):
        return self._chain(lambda ds: ds.random_shuffle(**kw))

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        base = self

        def gen():
            i = 0
            while times is None or i < times:
                yield from base._windows()
                i += 1
        p = DatasetPipeline(gen)
        return p

    # ----------------------------------------------------------- consuming

    def _windows(self) -> Iterator[Any]:
        for ds in self._gen_windows():
            for f in self._xforms:
                ds = f(ds)
            yield ds

    def iter_batches(self, **kw) -> Iterator[Any]:
        # each window rides the streaming executor via Dataset.iter_batches:
        # batches start flowing after the window's FIRST block chain
        # completes, not after the window fully executes
        for ds in self._windows():
            yield from ds.iter_batches(**kw)

    def iter_device_batches(self, **kw) -> Iterator[Any]:
        for ds in self._windows():
            yield from ds.iter_device_batches(**kw)

    def iter_rows(self) -> Iterator[Any]:
        for ds in self._windows():
            yield from ds.iter_rows()

    def iter_epochs(self) -> Iterator[Any]:
        yield from self._windows()

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(ds.count() for ds in self._windows())

    def split(self, n: int, *, equal: bool = True) -> List["DatasetPipeline"]:
        base = self

        def make(i):
            def gen():
                for ds in base._windows():
                    yield ds.split(n, equal=equal)[i]
            return DatasetPipeline(gen)
        return [make(i) for i in range(n)]
