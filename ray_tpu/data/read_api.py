"""Dataset creation: range/from_*/read_* factories.

Reference analogue: python/ray/data/read_api.py (range/read_parquet/
read_csv/... over datasource/). File reads are one remote task per file;
in-memory sources are chunked into ``parallelism`` blocks and put into the
object store.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import VALUE_COL, BlockAccessor
from ray_tpu.data.dataset import Dataset
from ray_tpu.data._internal.plan import ExecutionPlan

DEFAULT_PARALLELISM = 8


def _put_blocks(blocks: List[Any]) -> Dataset:
    import ray_tpu
    refs = [ray_tpu.put(b) for b in blocks]
    return Dataset(ExecutionPlan(refs))


def _chunk(n: int, parallelism: int) -> List[tuple]:
    parallelism = max(1, min(parallelism, n) if n else 1)
    per = n // parallelism
    rem = n % parallelism
    out, start = [], 0
    for i in builtins.range(parallelism):
        size = per + (1 if i < rem else 0)
        out.append((start, start + size))
        start += size
    return out


def range(n: int, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    blocks = [{VALUE_COL: np.arange(s, e, dtype=np.int64)}
              for s, e in _chunk(n, parallelism)]
    return _put_blocks(blocks)


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    blocks = []
    for s, e in _chunk(n, parallelism):
        base = np.arange(s, e, dtype=np.int64).reshape((-1,) + (1,) * len(shape))
        blocks.append({VALUE_COL: np.broadcast_to(
            base, (e - s,) + tuple(shape)).copy()})
    return _put_blocks(blocks)


def from_items(items: List[Any], *,
               parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    blocks = [list(items[s:e]) for s, e in _chunk(len(items), parallelism)]
    return _put_blocks(blocks)


def from_numpy(arr, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    if isinstance(arr, dict):
        n = len(next(iter(arr.values())))
        blocks = [{k: np.asarray(v)[s:e] for k, v in arr.items()}
                  for s, e in _chunk(n, parallelism)]
    else:
        arr = np.asarray(arr)
        blocks = [{VALUE_COL: arr[s:e]}
                  for s, e in _chunk(len(arr), parallelism)]
    return _put_blocks(blocks)


def from_pandas(dfs) -> Dataset:
    import pyarrow as pa
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _put_blocks([pa.Table.from_pandas(df) for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _put_blocks(list(tables))


# ------------------------------------------------------------ file readers


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, f"*{suffix}" if suffix else "*")
            out.extend(sorted(_glob.glob(pat)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def _read_files(paths, reader: Callable[[str], Any],
                parallelism: int) -> Dataset:
    """One task per file, coalesced to at most ``parallelism`` tasks when
    there are more files than that (a task then reads its group of files
    into one block). Splitting a single file across tasks is not supported."""
    import ray_tpu

    def _read_group(group):
        blocks = [BlockAccessor.batch_to_block(reader(p)) for p in group]
        return blocks[0] if len(blocks) == 1 else BlockAccessor.concat(blocks)

    if len(paths) > parallelism > 0:
        groups = [paths[s:e] for s, e in _chunk(len(paths), parallelism)]
    else:
        groups = [[p] for p in paths]
    t = ray_tpu.remote(_read_group)
    return Dataset(ExecutionPlan([t.remote(g) for g in groups]))


def read_parquet(paths, *, parallelism: int = DEFAULT_PARALLELISM,
                 columns: Optional[List[str]] = None) -> Dataset:
    files = _expand_paths(paths, ".parquet")

    def _r(path):
        import pyarrow.parquet as pq
        return pq.read_table(path, columns=columns)
    return _read_files(files, _r, parallelism)


def read_csv(paths, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def _r(path):
        import pyarrow.csv as pcsv
        return pcsv.read_csv(path)
    return _read_files(files, _r, parallelism)


def read_json(paths, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    files = _expand_paths(paths, ".json")

    def _r(path):
        import pyarrow.json as pjson
        return pjson.read_json(path)
    return _read_files(files, _r, parallelism)


def read_numpy(paths, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    files = _expand_paths(paths, ".npy")

    def _r(path):
        return {VALUE_COL: np.load(path)}
    return _read_files(files, _r, parallelism)


def read_images(paths, *, parallelism: int = DEFAULT_PARALLELISM,
                size: Optional[tuple] = None,
                mode: str = "RGB",
                include_paths: bool = False) -> Dataset:
    """Decode image files into an ``image`` column of HxWxC uint8
    arrays (reference: data/read_api.py read_images over
    ImageDatasource). ``size=(h, w)`` resizes on read — decode-time
    resize keeps the block memory bounded and the downstream arrays
    static-shaped (what a TPU input pipeline wants)."""
    exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")
    files = [f for f in _expand_paths(paths)
             if f.lower().endswith(exts)]
    if not files:
        raise ValueError(
            f"read_images: no files with extensions {exts} under "
            f"{paths!r}")

    def _r(path):
        from PIL import Image
        with Image.open(path) as im:
            im = im.convert(mode)
            if size is not None:
                im = im.resize((size[1], size[0]))
            arr = np.asarray(im, dtype=np.uint8)
        if arr.ndim == 2:  # grayscale modes keep the HxWxC contract
            arr = arr[:, :, None]
        row = {"image": arr}
        if include_paths:
            row["path"] = path
        return [row]
    return _read_files(files, _r, parallelism)


def read_mongo(uri: str, database: str, collection: str, *,
               parallelism: int = DEFAULT_PARALLELISM,
               pipeline: Optional[List[dict]] = None) -> Dataset:
    """Read a MongoDB collection (reference: read_api.py read_mongo over
    MongoDatasource). Gated on pymongo, which does not ship in this
    image — exactly like the reference gates on its connectors.

    The documents are materialized on the driver before blocking (the
    reference's MongoDatasource partitions by _id range into remote
    read tasks); acceptable for the modest collections this connector
    targets — use the file readers for bulk data."""
    try:
        import pymongo
    except ImportError as e:
        raise RuntimeError(
            "read_mongo requires pymongo (not installed)") from e
    client = pymongo.MongoClient(uri)
    try:
        coll = client[database][collection]
        docs = list(coll.aggregate(pipeline) if pipeline
                    else coll.find())
    finally:
        client.close()
    for d in docs:
        d.pop("_id", None)
    return from_items(docs, parallelism=parallelism)


def read_text(paths, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    files = _expand_paths(paths)

    def _r(path):
        with open(path) as f:
            return [line.rstrip("\n") for line in f]
    return _read_files(files, _r, parallelism)


def read_binary_files(paths, *,
                      parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    files = _expand_paths(paths)

    def _r(path):
        with open(path, "rb") as f:
            return [{"path": path, "bytes": f.read()}]
    return _read_files(files, _r, parallelism)


class Datasource:
    """Custom source ABC (reference: data/datasource/datasource.py).
    Implement ``get_read_tasks(parallelism) -> list of zero-arg callables``,
    each returning one block."""

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        raise NotImplementedError


def read_datasource(source: Datasource, *,
                    parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    import ray_tpu

    def _run(fn):
        return BlockAccessor.batch_to_block(fn())
    t = ray_tpu.remote(_run)
    tasks = source.get_read_tasks(parallelism)
    return Dataset(ExecutionPlan([t.remote(fn) for fn in tasks]))
