"""Client-side request router with backpressure.

Reference analogue: serve/_private/router.py:261 (Router,
assign_request:298) + the ReplicaSet power-of-queue logic (:62). Each
handle/proxy owns a Router that long-polls the controller for the live
replica membership and picks the least-loaded replica under
``max_concurrent_queries``, counting its own in-flight requests.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.actor import get_actor_by_id
from ray_tpu.serve._private.long_poll import LongPollClient


class ReplicaSet:
    """Tracks live replicas of one deployment + per-replica in-flight."""

    def __init__(self, deployment_name: str, max_concurrent_queries: int):
        self.deployment_name = deployment_name
        self.max_concurrent_queries = max_concurrent_queries
        self._replicas: List[Any] = []       # actor handles
        self._in_flight: Dict[str, int] = {}  # actor id hex -> count
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._rr = 0

    def update_replicas(self, replicas: List[Any],
                        max_concurrent_queries: Optional[int] = None):
        with self._cv:
            self._replicas = list(replicas)
            if max_concurrent_queries:
                self.max_concurrent_queries = max_concurrent_queries
            live = {r._id_hex for r in self._replicas}
            self._in_flight = {k: v for k, v in self._in_flight.items()
                               if k in live}
            self._cv.notify_all()

    def assign(self, timeout: float = 30.0):
        """Round-robin over replicas with < max_concurrent_queries of OUR
        in-flight requests; blocks when all are saturated."""
        deadline = time.time() + timeout
        with self._cv:
            while True:
                n = len(self._replicas)
                for off in range(n):
                    r = self._replicas[(self._rr + off) % n] if n else None
                    if r is None:
                        break
                    key = r._id_hex
                    if (self._in_flight.get(key, 0)
                            < self.max_concurrent_queries):
                        self._rr = (self._rr + off + 1) % n
                        self._in_flight[key] = \
                            self._in_flight.get(key, 0) + 1
                        return r
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no replica available for "
                        f"{self.deployment_name!r} within {timeout}s "
                        f"({n} replicas, all at "
                        f"{self.max_concurrent_queries} in-flight)")
                self._cv.wait(timeout=min(remaining, 1.0))

    def release(self, replica):
        with self._cv:
            key = replica._id_hex
            if key in self._in_flight:
                self._in_flight[key] -= 1
                if self._in_flight[key] <= 0:
                    self._in_flight.pop(key)
            self._cv.notify()


class Router:
    """Routes requests for many deployments; refreshed via long-poll."""

    def __init__(self, controller_handle):
        self._controller = controller_handle
        self._sets: Dict[str, ReplicaSet] = {}
        self._lock = threading.Lock()
        self._poller = LongPollClient(
            controller_handle, "route_table", self._on_update)
        # seed synchronously so the first request doesn't race the poller
        try:
            _, snapshot = ray_tpu.get(
                controller_handle.get_route_table.remote())
            if snapshot:
                self._on_update(snapshot)
        except Exception:
            pass

    def _on_update(self, snapshot: Optional[Dict[str, Any]]):
        if not snapshot:
            return
        with self._lock:
            for name, info in snapshot.items():
                replicas = [get_actor_by_id(h)
                            for h in info["replicas"]]
                s = self._sets.get(name)
                if s is None:
                    s = ReplicaSet(name, info["max_concurrent_queries"])
                    self._sets[name] = s
                s.update_replicas(replicas,
                                  info["max_concurrent_queries"])
            for gone in set(self._sets) - set(snapshot):
                self._sets.pop(gone)

    def replica_set(self, deployment_name: str) -> ReplicaSet:
        with self._lock:
            s = self._sets.get(deployment_name)
        if s is None:
            # force one refresh for deployments created after seeding
            _, snapshot = ray_tpu.get(
                self._controller.get_route_table.remote())
            self._on_update(snapshot)
            with self._lock:
                s = self._sets.get(deployment_name)
        if s is None:
            raise KeyError(f"unknown deployment {deployment_name!r}")
        return s

    def force_refresh(self):
        """Synchronous pull of the current route table (bypasses the
        long-poll latency) — used after a deploy barrier or when a
        request hits a dead replica."""
        try:
            _, snapshot = ray_tpu.get(
                self._controller.get_route_table.remote(), timeout=10.0)
            self._on_update(snapshot)
        except Exception:
            pass

    def assign_request(self, deployment_name: str, method_name: str,
                       args: tuple, kwargs: dict):
        """Pick a replica, fire the call, return (ObjectRef, done_cb)."""
        rs = self.replica_set(deployment_name)
        replica = rs.assign()
        ref = replica.handle_request.remote(method_name, args, kwargs)
        return ref, lambda: rs.release(replica)

    def stop(self):
        self._poller.stop()
