"""Client-side request router with load-aware replica selection.

Reference analogue: serve/_private/router.py:261 (Router,
assign_request:298) + the PowerOfTwoChoicesReplicaScheduler. Each
handle/proxy owns a Router that long-polls the controller for the live
replica membership AND per-replica load telemetry (queue depth + EWMA
service time, published on the ``replica_load`` key and piggybacked on
proxy responses), then picks replicas with power-of-two-choices over
reported queue lengths. When telemetry is stale the score falls back to
this router's own in-flight counts; ``RTPU_SERVE_ROUTING=round_robin``
(or a per-deployment ``routing_policy``) restores blind round-robin.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import ray_tpu
from ray_tpu import exceptions as rexc
from ray_tpu._private import tracing
from ray_tpu.actor import get_actor_by_id
from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.serve.exceptions import ReplicaOverloadedError

logger = logging.getLogger("ray_tpu.serve.router")

# transport/control-plane failures a routing refresh may legitimately
# hit; anything else (TypeError, KeyError, ...) is a bug and must raise
_REFRESH_ERRORS = (rexc.RayTpuError, TimeoutError, ConnectionError,
                   OSError)


def _load_staleness_s() -> float:
    try:
        return float(os.environ.get("RTPU_SERVE_LOAD_STALENESS_S", 5.0))
    except ValueError:
        return 5.0


def _default_policy() -> str:
    return os.environ.get("RTPU_SERVE_ROUTING", "p2c").strip().lower()


def _call_under_span(span: Optional["tracing.Span"], fn):
    """Run ``fn`` (an actor-call submit) with ``span`` installed as the
    caller's current trace ctx: the call's task-event record then joins
    the serve trace (visible under RTPU_ACTOR_TASK_EVENTS=1) instead of
    the caller process's root trace."""
    if span is None:
        return fn()
    from ray_tpu._private import worker as worker_mod
    w = worker_mod._global_worker
    if w is None:
        return fn()
    prev = getattr(w.task_context, "trace", None)
    w.task_context.trace = span.trace_ctx()
    try:
        return fn()
    finally:
        w.task_context.trace = prev


def is_overload_error(err: BaseException) -> bool:
    """True when an exception raised at ``get()`` means the replica shed
    the request (retriable on another replica)."""
    if isinstance(err, ReplicaOverloadedError):
        return True
    cause = getattr(err, "cause", None)
    if isinstance(cause, ReplicaOverloadedError):
        return True
    # defensive: the cause survives the object plane only if picklable;
    # fall back to the type name in the captured traceback
    return (isinstance(err, rexc.TaskError)
            and "ReplicaOverloadedError" in str(err))


class ReplicaSet:
    """Tracks live replicas of one deployment: per-replica local
    in-flight counts plus replica-reported load telemetry."""

    def __init__(self, deployment_name: str, max_concurrent_queries: int):
        self.deployment_name = deployment_name
        self.max_concurrent_queries = max_concurrent_queries
        self.routing_policy: Optional[str] = None  # None → env default
        self._replicas: List[Any] = []       # actor handles
        self._in_flight: Dict[str, int] = {}  # actor id hex -> count
        # actor id hex -> "prefill"|"decode" (controller-assigned, only
        # for disaggregated LLM deployments; empty otherwise)
        self._roles: Dict[str, str] = {}
        # actor id hex -> {"queue_len", "ewma_s", "ts"} as reported by
        # the replica (long-poll refresh or response piggyback)
        self._reports: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._rr = 0
        self._rng = random.Random()

    def update_replicas(self, replicas: List[Any],
                        max_concurrent_queries: Optional[int] = None,
                        routing_policy: Optional[str] = None,
                        replica_roles: Optional[Dict[str, str]] = None):
        with self._cv:
            self._replicas = list(replicas)
            if max_concurrent_queries:
                self.max_concurrent_queries = max_concurrent_queries
            if routing_policy is not None:
                self.routing_policy = routing_policy
            live = {r._id_hex for r in self._replicas}
            self._roles = {k: v for k, v in (replica_roles or {}).items()
                           if k in live}
            self._in_flight = {k: v for k, v in self._in_flight.items()
                               if k in live}
            self._reports = {k: v for k, v in self._reports.items()
                             if k in live}
            self._cv.notify_all()

    def member_ids(self) -> Set[str]:
        with self._cv:
            return {r._id_hex for r in self._replicas}

    def role_members(self, role: str) -> Set[str]:
        with self._cv:
            return {k for k, v in self._roles.items() if v == role}

    def disaggregated(self) -> bool:
        """True when the controller published a role split with at
        least one live prefill AND one live decode replica — the
        router's cue to run the two-hop admission."""
        with self._cv:
            roles = set(self._roles.values())
        return "prefill" in roles and "decode" in roles

    def record_report(self, replica_id: str, queue_len: float,
                      ewma_s: float = 0.0, ts: Optional[float] = None):
        """Fold in a replica-reported load sample (long-poll snapshot or
        response piggyback); newer timestamps win."""
        ts = time.time() if ts is None else ts
        with self._cv:
            cur = self._reports.get(replica_id)
            if cur is None or ts >= cur["ts"]:
                self._reports[replica_id] = {"queue_len": float(queue_len),
                                             "ewma_s": float(ewma_s or 0.0),
                                             "ts": ts}

    # ---- selection ----

    def _score(self, key: str, now: float) -> Tuple[float, float]:
        """(queue score, EWMA tiebreak). Fresh replica-reported queue
        depth is the primary signal — it sees load from EVERY router —
        plus our own in-flight (requests the report can't know about
        yet). Stale telemetry degrades to local counts only."""
        local = self._in_flight.get(key, 0)
        rep = self._reports.get(key)
        if rep is not None and now - rep["ts"] <= _load_staleness_s():
            return (rep["queue_len"] + local, rep["ewma_s"])
        return (float(local), 0.0)

    def _pick(self, candidates: List[Any]) -> Any:
        policy = self.routing_policy or _default_policy()
        if policy == "round_robin" or len(candidates) == 1:
            # preserve arrival order relative to the full replica list
            n = len(self._replicas)
            cand_ids = {c._id_hex for c in candidates}
            for off in range(n):
                r = self._replicas[(self._rr + off) % n]
                if r._id_hex in cand_ids:
                    self._rr = (self._rr + off + 1) % n
                    return r
            return candidates[0]
        # power of two choices: sample two distinct replicas, take the
        # one with the lower queue score (EWMA service time tiebreaks)
        now = time.time()
        a, b = self._rng.sample(candidates, 2)
        sa, sb = self._score(a._id_hex, now), self._score(b._id_hex, now)
        return a if sa <= sb else b

    def assign(self, timeout: float = 30.0,
               exclude: Optional[Set[str]] = None):
        """Pick a replica with < max_concurrent_queries of OUR in-flight
        requests; blocks when all are saturated (backpressure)."""
        deadline = time.time() + timeout
        exclude = exclude or set()
        with self._cv:
            while True:
                candidates = [
                    r for r in self._replicas
                    if r._id_hex not in exclude
                    and (self._in_flight.get(r._id_hex, 0)
                         < self.max_concurrent_queries)]
                if candidates:
                    r = self._pick(candidates)
                    key = r._id_hex
                    self._in_flight[key] = self._in_flight.get(key, 0) + 1
                    return r
                remaining = deadline - time.time()
                if remaining <= 0:
                    # build the message from CURRENT state under the
                    # lock — update_replicas may have raced the wait
                    # loop, and a stale count here sends the operator
                    # chasing the wrong replica set
                    n = len(self._replicas)
                    n_excluded = sum(1 for r in self._replicas
                                     if r._id_hex in exclude)
                    in_flight = sum(self._in_flight.get(r._id_hex, 0)
                                    for r in self._replicas)
                    raise TimeoutError(
                        f"no replica available for "
                        f"{self.deployment_name!r} within {timeout}s "
                        f"({n} replicas, {n_excluded} excluded, "
                        f"{in_flight} total in-flight, cap "
                        f"{self.max_concurrent_queries}/replica)")
                self._cv.wait(timeout=min(remaining, 1.0))

    def release(self, replica, service_time_s: Optional[float] = None):
        with self._cv:
            key = replica._id_hex
            if key in self._in_flight:
                self._in_flight[key] -= 1
                if self._in_flight[key] <= 0:
                    self._in_flight.pop(key)
            self._cv.notify()


class Router:
    """Routes requests for many deployments; membership and load
    telemetry refreshed via long-poll."""

    def __init__(self, controller_handle):
        self._controller = controller_handle
        self._sets: Dict[str, ReplicaSet] = {}
        self._lock = threading.Lock()
        self._poller = LongPollClient(
            controller_handle, "route_table", self._on_update)
        self._load_poller = LongPollClient(
            controller_handle, "replica_load", self._on_load_update)
        # seed synchronously so the first request doesn't race the poller
        try:
            _, snapshot = ray_tpu.get(
                controller_handle.get_route_table.remote())
            if snapshot:
                self._on_update(snapshot)
        except _REFRESH_ERRORS as e:
            logger.warning(
                "router: initial route-table seed from controller failed "
                "(%s: %s); falling back to the long-poll — the first "
                "request may see a brief 'unknown deployment' window",
                type(e).__name__, e)

    def _on_update(self, snapshot: Optional[Dict[str, Any]]):
        if not snapshot:
            return
        with self._lock:
            for name, info in snapshot.items():
                replicas = []
                for h in info["replicas"]:
                    try:
                        replicas.append(get_actor_by_id(h))
                    except (ValueError, *_REFRESH_ERRORS) as e:
                        # replica died between table publish and our
                        # resolve; the controller's health check will
                        # push a corrected table
                        logger.warning(
                            "router: replica %s of %r unresolvable "
                            "(%s: %s); skipping until the next table "
                            "update", h[:8], name, type(e).__name__, e)
                s = self._sets.get(name)
                if s is None:
                    s = ReplicaSet(name, info["max_concurrent_queries"])
                    self._sets[name] = s
                s.update_replicas(replicas,
                                  info["max_concurrent_queries"],
                                  info.get("routing_policy"),
                                  info.get("replica_roles"))
            for gone in set(self._sets) - set(snapshot):
                self._sets.pop(gone)

    def _on_load_update(self, snapshot: Optional[Dict[str, Any]]):
        """``replica_load`` long-poll: {deployment: {replica_id:
        {queue_len, ewma_s, ts}}} collected by the controller."""
        if not snapshot:
            return
        with self._lock:
            sets = dict(self._sets)
        for name, per_replica in snapshot.items():
            s = sets.get(name)
            if s is None:
                continue
            for replica_id, load in (per_replica or {}).items():
                try:
                    s.record_report(replica_id, load["queue_len"],
                                    load.get("ewma_s", 0.0),
                                    load.get("ts"))
                except (KeyError, TypeError):
                    continue

    def replica_set(self, deployment_name: str) -> ReplicaSet:
        with self._lock:
            s = self._sets.get(deployment_name)
        if s is None:
            # force one refresh for deployments created after seeding —
            # resilient to a controller outage: a KNOWN deployment keeps
            # routing from the cached set; only a genuinely unseen one
            # needs the controller up to resolve
            try:
                _, snapshot = ray_tpu.get(
                    self._controller.get_route_table.remote(),
                    timeout=10.0)
                self._on_update(snapshot)
            except _REFRESH_ERRORS as e:
                raise KeyError(
                    f"unknown deployment {deployment_name!r} and the "
                    f"controller is unreachable to resolve it "
                    f"({type(e).__name__}: {e})") from e
            with self._lock:
                s = self._sets.get(deployment_name)
        if s is None:
            raise KeyError(f"unknown deployment {deployment_name!r}")
        return s

    def force_refresh(self):
        """Synchronous pull of the current route table (bypasses the
        long-poll latency) — used after a deploy barrier or when a
        request hits a dead replica."""
        try:
            _, snapshot = ray_tpu.get(
                self._controller.get_route_table.remote(), timeout=10.0)
            self._on_update(snapshot)
        except _REFRESH_ERRORS as e:
            logger.warning(
                "router: route-table refresh failed for deployments %s "
                "(%s: %s); keeping the previous table until the "
                "long-poll catches up", sorted(self._sets),
                type(e).__name__, e)

    def assign_request(self, deployment_name: str, method_name: str,
                       args: tuple, kwargs: dict,
                       timeout: float = 30.0,
                       exclude: Optional[Set[str]] = None,
                       trace_parent: Optional[Dict[str, str]] = None):
        """Pick a replica, fire the call; returns (ObjectRef, done_cb,
        replica handle).

        Tracing: each call opens a ``serve.request`` root span (trace
        id = the ``__rtpu_request_id__`` kwarg when tagged, so the SLO
        report links a slow request straight to its trace) with a
        ``router.assign`` child covering replica selection — the wait
        for a free slot IS the router-side queueing the analyzer must
        see. The root closes in the done callback, i.e. at the same
        instant the caller observes completion. ``trace_parent`` nests
        this request under an enclosing span (the HTTP proxy's)."""
        rs = self.replica_set(deployment_name)
        root = None
        sampled = False
        if tracing.enabled():
            from ray_tpu.serve._private.replica import (REQUEST_ID_KWARG,
                                                        TRACE_CTX_KWARG)
            rid = (kwargs or {}).get(REQUEST_ID_KWARG)
            root = tracing.Span(
                (trace_parent or {}).get("trace_id") or rid
                or tracing.new_trace_id(),
                f"serve.request:{deployment_name}",
                parent_span_id=(trace_parent or {}).get("span_id"),
                kind="serve.request", phase="transfer",
                attrs={"deployment": deployment_name,
                       "request_id": rid})
            # head-sampling decides HERE whether the request is traced
            # end to end: only sampled requests pay for context
            # propagation and child spans; an unsampled root costs two
            # clock reads and is still tail-kept when slow
            sampled = tracing.sampled(root.trace_id)
            if sampled:
                kwargs = dict(kwargs) if kwargs else {}
                kwargs[TRACE_CTX_KWARG] = root.child_ctx()
        t_assign = time.time()
        try:
            replica = rs.assign(timeout=timeout, exclude=exclude)
        except BaseException:
            if root is not None:
                root.finish("error")
            raise
        if sampled and time.time() - t_assign > 1e-4:
            # the wait for a free replica slot is router-side queueing;
            # a no-wait assign is noise and not worth a span
            tracing.record_span(
                root.trace_id, tracing.new_span_id(), "router.assign",
                parent_span_id=root.span_id, kind="serve.router",
                phase="schedule", start_ts=t_assign,
                end_ts=time.time())
        ref = _call_under_span(
            root if sampled else None,
            lambda: replica.handle_request.remote(
                method_name, args, kwargs))
        if root is None:
            return ref, lambda: rs.release(replica), replica

        def done():
            rs.release(replica)
            root.finish()
        return ref, done, replica

    def execute_request(self, deployment_name: str, method_name: str,
                        args: tuple, kwargs: dict, *,
                        get_timeout: float = 60.0,
                        assign_timeout: float = 30.0,
                        overload_retries: Optional[int] = None,
                        request_id: Optional[str] = None,
                        trace_parent: Optional[Dict[str, str]] = None
                        ) -> Any:
        """Synchronous request with overload retry — the proxy hot path.

        Uses the replica's envelope method so each response piggybacks
        current load into this router's telemetry. A shed request
        (``ReplicaOverloadedError``) is retried on a different replica
        up to ``overload_retries`` times (env
        ``RTPU_SERVE_OVERLOAD_RETRIES``, default 3); exhaustion
        re-raises the overload error for the caller to map (the HTTP
        proxy returns 503).

        ``request_id`` tags the request end to end: it rides the
        reserved ``__rtpu_request_id__`` kwarg into the replica (which
        strips it, ledgers it, and echoes it in the envelope), and
        every overload retry reuses the SAME id — retries are one
        logical request, and the per-request join in
        ``gameday/reconcile.py`` counts them that way (N shed records
        + at most one completion for one id)."""
        if request_id is not None:
            from ray_tpu.serve._private.replica import REQUEST_ID_KWARG
            kwargs = {**(kwargs or {}), REQUEST_ID_KWARG: request_id}
        root = None
        sampled = False
        if tracing.enabled():
            from ray_tpu.serve._private.replica import TRACE_CTX_KWARG
            root = tracing.Span(
                (trace_parent or {}).get("trace_id") or request_id
                or tracing.new_trace_id(),
                f"serve.request:{deployment_name}",
                parent_span_id=(trace_parent or {}).get("span_id"),
                kind="serve.request", phase="transfer",
                attrs={"deployment": deployment_name,
                       "request_id": request_id})
            sampled = tracing.sampled(root.trace_id)
            if sampled:
                kwargs = {**(kwargs or {}),
                          TRACE_CTX_KWARG: root.child_ctx()}
        try:
            out = self._execute_attempts(
                deployment_name, method_name, args, kwargs,
                get_timeout=get_timeout, assign_timeout=assign_timeout,
                overload_retries=overload_retries,
                root=root if sampled else None)
        except BaseException:
            if root is not None:
                root.finish("error")
            raise
        if root is not None:
            root.finish()
        return out

    def _execute_attempts(self, deployment_name: str, method_name: str,
                          args: tuple, kwargs: dict, *,
                          get_timeout: float, assign_timeout: float,
                          overload_retries: Optional[int],
                          root: Optional["tracing.Span"] = None) -> Any:
        if overload_retries is None:
            try:
                overload_retries = int(os.environ.get(
                    "RTPU_SERVE_OVERLOAD_RETRIES", 3))
            except ValueError:
                overload_retries = 3
        rs = self.replica_set(deployment_name)
        exclude: Set[str] = set()
        last_err: Optional[BaseException] = None
        for _ in range(max(1, overload_retries + 1)):
            t_assign = time.time()
            replica = rs.assign(timeout=assign_timeout,
                                exclude=exclude)
            if root is not None and time.time() - t_assign > 1e-4:
                tracing.record_span(
                    root.trace_id, tracing.new_span_id(),
                    "router.assign", parent_span_id=root.span_id,
                    kind="serve.router", phase="schedule",
                    start_ts=t_assign, end_ts=time.time())
            ref = _call_under_span(
                root, lambda: replica.handle_request_with_load.remote(
                    method_name, args, kwargs))
            try:
                out = ray_tpu.get(ref, timeout=get_timeout)
            except Exception as e:
                if is_overload_error(e):
                    # shed: the replica is full — don't pick it again
                    # for this request, try the others
                    exclude.add(replica._id_hex)
                    rs.record_report(replica._id_hex,
                                     queue_len=float("inf"))
                    last_err = e
                    continue
                raise
            finally:
                rs.release(replica)
            if isinstance(out, dict) and "__serve_result__" in out:
                load = out.get("__serve_load__")
                if isinstance(load, dict):
                    rs.record_report(replica._id_hex,
                                     load.get("queue_len", 0),
                                     load.get("ewma_s", 0.0),
                                     load.get("ts"))
                return out["__serve_result__"]
            return out
        raise last_err

    # ------------------------------------------------------- streaming

    def open_stream(self, deployment_name: str, payload: Any, *,
                    request_id: Optional[str] = None,
                    assign_timeout: float = 30.0,
                    open_timeout: float = 30.0,
                    overload_retries: Optional[int] = None,
                    trace_parent: Optional[Dict[str, str]] = None
                    ) -> "ReplicaStream":
        """Start a token stream (serve/llm): pick a replica with the
        same load-aware admission as a unary request, call its
        ``__llm_open__``, and return a ``ReplicaStream`` pinned to that
        replica (sequence state is replica-local — every subsequent
        poll MUST go to the same one). A shed open retries on other
        replicas exactly like ``execute_request``; the router in-flight
        slot is held only for the open call — once the engine admitted
        the sequence, ITS admission (waiting queue + KV pool) is the
        backpressure, and polls are cheap cursor reads.

        Tracing: the ``serve.request`` root span stays open until the
        stream finishes, so the trace covers the full generation, not
        just the admission RPC."""
        if overload_retries is None:
            try:
                overload_retries = int(os.environ.get(
                    "RTPU_SERVE_OVERLOAD_RETRIES", 3))
            except ValueError:
                overload_retries = 3
        kwargs: Dict[str, Any] = {}
        if request_id is not None:
            from ray_tpu.serve._private.replica import REQUEST_ID_KWARG
            kwargs[REQUEST_ID_KWARG] = request_id
        root = None
        sampled = False
        if tracing.enabled():
            from ray_tpu.serve._private.replica import TRACE_CTX_KWARG
            root = tracing.Span(
                (trace_parent or {}).get("trace_id") or request_id
                or tracing.new_trace_id(),
                f"serve.request:{deployment_name}",
                parent_span_id=(trace_parent or {}).get("span_id"),
                kind="serve.request", phase="transfer",
                attrs={"deployment": deployment_name,
                       "request_id": request_id, "streaming": True})
            sampled = tracing.sampled(root.trace_id)
            if sampled:
                kwargs[TRACE_CTX_KWARG] = root.child_ctx()
        rs = self.replica_set(deployment_name)
        try:
            if rs.disaggregated():
                stream = self._open_disagg(
                    deployment_name, rs, payload, kwargs,
                    root if sampled else None,
                    assign_timeout=assign_timeout,
                    open_timeout=open_timeout,
                    overload_retries=overload_retries)
                if stream is not None:
                    stream._root = root
                    return stream
                logger.warning(
                    "llm disagg: two-hop admission unavailable for %r; "
                    "falling back to unified __llm_open__",
                    deployment_name)
            return self._open_unified(
                deployment_name, rs, payload, kwargs,
                root, sampled, assign_timeout=assign_timeout,
                open_timeout=open_timeout,
                overload_retries=overload_retries)
        except BaseException:
            if root is not None:
                root.finish("error")
            raise

    def _open_unified(self, deployment_name, rs, payload, kwargs,
                      root, sampled, *, assign_timeout, open_timeout,
                      overload_retries) -> "ReplicaStream":
        exclude: Set[str] = set()
        last_err: Optional[BaseException] = None
        for _ in range(max(1, overload_retries + 1)):
            replica = rs.assign(timeout=assign_timeout,
                                exclude=exclude)
            ref = _call_under_span(
                root if sampled else None,
                lambda: replica.handle_request_with_load.remote(
                    "__llm_open__", (payload,), kwargs))
            try:
                out = ray_tpu.get(ref, timeout=open_timeout)
            except Exception as e:
                if is_overload_error(e):
                    exclude.add(replica._id_hex)
                    rs.record_report(replica._id_hex,
                                     queue_len=float("inf"))
                    last_err = e
                    continue
                raise
            finally:
                rs.release(replica)
            if isinstance(out, dict) and "__serve_result__" in out:
                load = out.get("__serve_load__")
                if isinstance(load, dict):
                    rs.record_report(replica._id_hex,
                                     load.get("queue_len", 0),
                                     load.get("ewma_s", 0.0),
                                     load.get("ts"))
                out = out["__serve_result__"]
            return ReplicaStream(deployment_name, replica,
                                 out["stream_id"], root)
        raise last_err

    def _hop(self, rs, payload, kwargs, root, role: str,
             method: str, *, assign_timeout, open_timeout,
             overload_retries) -> Optional[Tuple[Any, Any]]:
        """One admission hop against the ``role`` sub-fleet: assign a
        replica of that role, call ``method``, unwrap the load
        envelope. Returns (replica, result) or None when the hop can't
        complete retriably (role empty / all shed / assign timeout) —
        the caller falls back. Non-overload errors raise."""
        members = rs.role_members(role)
        if not members:
            return None
        exclude = rs.member_ids() - members
        for _ in range(max(1, overload_retries + 1)):
            try:
                replica = rs.assign(timeout=assign_timeout,
                                    exclude=exclude)
            except TimeoutError:
                return None   # role sub-fleet saturated: fall back
            ref = _call_under_span(
                root, lambda: replica.handle_request_with_load.remote(
                    method, (payload,), kwargs))
            try:
                out = ray_tpu.get(ref, timeout=open_timeout)
            except Exception as e:
                if is_overload_error(e):
                    exclude.add(replica._id_hex)
                    rs.record_report(replica._id_hex,
                                     queue_len=float("inf"))
                    continue
                raise
            finally:
                rs.release(replica)
            if isinstance(out, dict) and "__serve_result__" in out:
                load = out.get("__serve_load__")
                if isinstance(load, dict):
                    rs.record_report(replica._id_hex,
                                     load.get("queue_len", 0),
                                     load.get("ewma_s", 0.0),
                                     load.get("ts"))
                out = out["__serve_result__"]
            return replica, out
        return None

    def _open_disagg(self, deployment_name, rs, payload, kwargs,
                     root, *, assign_timeout, open_timeout,
                     overload_retries) -> Optional["ReplicaStream"]:
        """Two-hop disaggregated admission: ``__llm_prefill__`` on a
        prefill-role replica (prompt + first token + KV snapshot into a
        plasmax ring slot), then ``__llm_adopt__`` on a decode-role
        replica (rebind the shipped pages; re-prefill on a torn frame)
        — the stream pins to the DECODE replica. Any structural
        failure returns None and the caller falls back to the unified
        single-hop open, which is always correct."""
        try:
            got = self._hop(rs, payload, kwargs, root, "prefill",
                            "__llm_prefill__",
                            assign_timeout=assign_timeout,
                            open_timeout=open_timeout,
                            overload_retries=overload_retries)
            if got is None:
                return None
            _prefill_replica, handoff = got
            got = self._hop(rs, handoff, kwargs, root, "decode",
                            "__llm_adopt__",
                            assign_timeout=assign_timeout,
                            open_timeout=open_timeout,
                            overload_retries=overload_retries)
            if got is None:
                return None
            decode_replica, out = got
            return ReplicaStream(deployment_name, decode_replica,
                                 out["stream_id"], None)
        except Exception as e:
            # correctness is owned by the unified fallback; the two-hop
            # path only ever improves latency, so any error degrades
            logger.warning(
                "llm disagg: two-hop admission failed for %r "
                "(%s: %s); falling back to unified open",
                deployment_name, type(e).__name__, e)
            return None

    def stop(self):
        self._poller.stop()
        self._load_poller.stop()


class ReplicaStream:
    """A token stream pinned to one replica (serve/llm sequences are
    replica-local state). Iterating yields chunk dicts
    ``{"tokens", "text"?, "cursor", "done", ...}``; the final chunk has
    ``done=True`` and a ``finish_reason``. A replica death mid-stream
    raises ``StreamBrokenError`` carrying the progress so far — the
    caller retries the WHOLE request or fails cleanly; a stream is
    never silently truncated."""

    def __init__(self, deployment_name: str, replica, stream_id: str,
                 root_span=None):
        self.deployment_name = deployment_name
        self.replica = replica
        self.stream_id = stream_id
        self.cursor = 0
        self.done = False
        self.finish_reason: Optional[str] = None
        self._root = root_span

    def _finish_span(self, status: str = "ok"):
        if self._root is not None:
            self._root.finish(status)
            self._root = None

    def next_chunk(self, max_wait_s: float = 10.0,
                   get_timeout: float = 30.0) -> Dict[str, Any]:
        """One cursor poll; returns the next chunk (possibly empty on
        an idle wait timeout — call again)."""
        if self.done:
            return {"tokens": [], "cursor": self.cursor, "done": True,
                    "finish_reason": self.finish_reason}
        try:
            chunk = ray_tpu.get(
                self.replica.handle_request.remote(
                    "__llm_next__", (self.stream_id, self.cursor,
                                     max_wait_s), {}),
                timeout=get_timeout)
        except BaseException as e:
            self._finish_span("error")
            if isinstance(e, (rexc.ActorDiedError,
                              rexc.ActorUnavailableError, KeyError,
                              rexc.TaskError)):
                from ray_tpu.serve.exceptions import StreamBrokenError
                raise StreamBrokenError(
                    self.deployment_name, self.cursor,
                    f"{type(e).__name__}: {e}".split("\n")[0]) from e
            raise
        self.cursor = chunk.get("cursor", self.cursor)
        if chunk.get("done"):
            self.done = True
            self.finish_reason = chunk.get("finish_reason")
            if chunk.get("error"):
                self._finish_span("error")
                from ray_tpu.serve.exceptions import StreamBrokenError
                raise StreamBrokenError(self.deployment_name,
                                        self.cursor, chunk["error"])
            self._finish_span("ok")
        return chunk

    def __iter__(self):
        while not self.done:
            chunk = self.next_chunk()
            if chunk.get("tokens") or chunk.get("done"):
                yield chunk

    def cancel(self):
        self._finish_span("error")
        try:
            self.replica.handle_request.remote(
                "__llm_cancel__", (self.stream_id,), {})
        except Exception:
            pass
        self.done = True
