"""Replica autoscaling from queue-length metrics.

Reference analogue: serve/_private/autoscaling_policy.py (policy on
per-replica ongoing-request metrics from autoscaling_metrics.py).

The controller feeds ``get_decision`` the summed per-replica
``queue_len`` (executing requests + the bounded ingress waiting room,
from ``ReplicaActor.get_load``) rather than ongoing requests alone: a
replica whose execution slots are saturated keeps registering rising
load through its queue, so backpressure shows up as scale-out pressure
instead of being invisible behind the concurrency cap.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 10
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    smoothing_factor: float = 1.0


class AutoscalingPolicy:
    """Desired replicas ∝ observed ongoing requests / target-per-replica,
    with hysteresis delays so transient spikes don't flap the fleet."""

    def __init__(self, config: AutoscalingConfig):
        self.config = config
        self._above_since = None
        self._below_since = None

    def get_decision(self, current_replicas: int,
                     total_ongoing: float, now: float) -> int:
        """``total_ongoing`` is the deployment-wide queue depth
        (executing + queued across replicas)."""
        c = self.config
        if current_replicas == 0:
            return c.min_replicas
        raw = total_ongoing / max(
            c.target_num_ongoing_requests_per_replica, 1e-9)
        desired = current_replicas + c.smoothing_factor * (
            raw - current_replicas)
        desired = int(min(max(math.ceil(desired), c.min_replicas),
                          c.max_replicas))
        if desired > current_replicas:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since >= c.upscale_delay_s:
                self._above_since = None
                return desired
            return current_replicas
        if desired < current_replicas:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since >= c.downscale_delay_s:
                self._below_since = None
                return desired
            return current_replicas
        self._above_since = self._below_since = None
        return current_replicas
