"""Replica autoscaling from queue-length + LLM workload metrics.

Reference analogue: serve/_private/autoscaling_policy.py (policy on
per-replica ongoing-request metrics from autoscaling_metrics.py).

The controller feeds ``get_decision`` the summed per-replica
``queue_len`` (executing requests + the bounded ingress waiting room,
from ``ReplicaActor.get_load``) rather than ongoing requests alone: a
replica whose execution slots are saturated keeps registering rising
load through its queue, so backpressure shows up as scale-out pressure
instead of being invisible behind the concurrency cap.

LLM deployments (serve/llm) additionally report ``signals`` — the
aggregated engine telemetry the replica load rows carry
(``tokens_per_s``, ``kv_occupancy``, running/waiting sequences).
Queue depth alone is a poor LLM signal: a decode batch of long
sequences holds few *requests* but saturates the KV pool and the
chip. With ``target_tokens_per_s_per_replica`` and/or
``target_kv_occupancy`` set, desired capacity is the MAX over all
configured signals — the binding constraint scales the fleet.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 10
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    smoothing_factor: float = 1.0
    # LLM signals (None = queue depth only). tokens/s is a THROUGHPUT
    # target per replica; occupancy is the fraction of the paged KV
    # pool in use a replica should sit at (0 < target <= 1).
    target_tokens_per_s_per_replica: Optional[float] = None
    target_kv_occupancy: Optional[float] = None


class AutoscalingPolicy:
    """Desired replicas ∝ observed ongoing requests / target-per-replica,
    with hysteresis delays so transient spikes don't flap the fleet."""

    def __init__(self, config: AutoscalingConfig):
        self.config = config
        self._above_since = None
        self._below_since = None

    def _desired_from_signals(self, current: int,
                              signals: Optional[Dict[str, Any]]
                              ) -> float:
        """Raw desired replica count from the LLM telemetry, before
        smoothing/clamping: the max over configured targets."""
        c = self.config
        raw = 0.0
        if not signals:
            return raw
        if c.target_tokens_per_s_per_replica:
            # tokens/s the fleet PRODUCED; cache-hit tokens/s (prefix
            # cache skipping prefill work) count as served demand the
            # fleet absorbed without compute — both are throughput the
            # target has to cover (docs/LLM_SERVING.md)
            served = (float(signals.get("tokens_per_s", 0.0))
                      + float(signals.get("cache_hit_tokens_per_s", 0.0)))
            raw = max(raw, served / c.target_tokens_per_s_per_replica)
        if c.target_kv_occupancy:
            # occupancy is per-replica-average: current fleet holding
            # occ of its pools needs current * occ / target replicas
            occ = float(signals.get("kv_occupancy", 0.0))
            raw = max(raw, current * occ / c.target_kv_occupancy)
        per_role = signals.get("per_role")
        if per_role and c.target_tokens_per_s_per_replica:
            # disaggregated fleets: size each role sub-fleet for ITS
            # load, then sum — a saturated decode tier must not hide
            # behind idle prefill replicas in the fleet-wide mean
            need = 0.0
            for role_row in per_role.values():
                need += max(1.0, math.ceil(
                    float(role_row.get("tokens_per_s", 0.0))
                    / c.target_tokens_per_s_per_replica))
            raw = max(raw, need)
        return raw

    def get_decision(self, current_replicas: int,
                     total_ongoing: float, now: float,
                     signals: Optional[Dict[str, Any]] = None) -> int:
        """``total_ongoing`` is the deployment-wide queue depth
        (executing + queued across replicas); ``signals`` the
        aggregated LLM telemetry when the deployment reports it."""
        c = self.config
        if current_replicas == 0:
            return c.min_replicas
        raw = total_ongoing / max(
            c.target_num_ongoing_requests_per_replica, 1e-9)
        raw = max(raw, self._desired_from_signals(current_replicas,
                                                  signals))
        desired = current_replicas + c.smoothing_factor * (
            raw - current_replicas)
        desired = int(min(max(math.ceil(desired), c.min_replicas),
                          c.max_replicas))
        if desired > current_replicas:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since >= c.upscale_delay_s:
                self._above_since = None
                return desired
            return current_replicas
        if desired < current_replicas:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since >= c.downscale_delay_s:
                self._below_since = None
                return desired
            return current_replicas
        self._above_since = self._below_since = None
        return current_replicas
