"""Serve control-plane journal — durable controller state in the GCS.

Reference analogue: serve's KVStore-backed checkpoints
(serve/_private/storage/kv_store.py + ServeController checkpoint
writes in deployment_state.py): the controller journals its target
state (deployment configs, versions, replica membership) to the GCS KV
table on every mutation, so a controller restarted by the GCS actor
state machine (``max_restarts=-1``) rebuilds ``_deployments`` from the
journal and re-adopts the live detached ``SERVE_REPLICA::*`` actors
instead of restarting the data plane.

Layout (all under one prefix so teardown is a single prefix delete):

    @serve/meta            -> {"replica_seq": int, "namespace": str}
    @serve/dep/<name>      -> {"config", "version", "target_replicas",
                               "replicas": [{"name", "id", "version",
                                             "draining"}], ...}

Values are cloudpickle blobs: deployment configs carry cloudpickled
callables and ``DeploymentHandle``/``ActorHandle`` init args, which the
msgpack wire cannot represent directly. The GCS persists the KV table
write-through (gcs_store), so the journal survives GCS restarts too
when the cluster runs a file-backed store.

Every writer is best-effort-with-logging: a journal write failure must
degrade durability, never availability (the in-memory state is still
authoritative for the running controller).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

import cloudpickle

logger = logging.getLogger("ray_tpu.serve.journal")

PREFIX = "@serve/"
META_KEY = PREFIX + "meta"
DEP_PREFIX = PREFIX + "dep/"


def _gcs_call(method: str, payload: Dict[str, Any], timeout: float = 10.0):
    from ray_tpu._private.worker import global_worker
    w = global_worker()
    return w.call_sync(w.gcs, method, payload, timeout=timeout)


def put_deployment(name: str, record: Dict[str, Any]) -> None:
    _gcs_call("kv_put", {"key": DEP_PREFIX + name,
                         "value": cloudpickle.dumps(record)})


def delete_deployment(name: str) -> None:
    _gcs_call("kv_del", {"key": DEP_PREFIX + name})


def put_meta(meta: Dict[str, Any]) -> None:
    _gcs_call("kv_put", {"key": META_KEY,
                         "value": cloudpickle.dumps(meta)})


def load_all() -> Tuple[Optional[Dict[str, Any]], Dict[str, Dict[str, Any]]]:
    """One bulk read of the whole journal: (meta | None, {name: record}).

    Corrupt/unpicklable entries are skipped with a warning — a torn
    record for one deployment must not block recovery of the others.
    """
    reply = _gcs_call("kv_get_prefix", {"prefix": PREFIX}, timeout=30.0)
    meta: Optional[Dict[str, Any]] = None
    deps: Dict[str, Dict[str, Any]] = {}
    for key, value in reply.get("items") or []:
        try:
            obj = cloudpickle.loads(value)
        except Exception:
            logger.warning("serve journal: skipping corrupt entry %r", key,
                           exc_info=True)
            continue
        if key == META_KEY:
            meta = obj
        elif key.startswith(DEP_PREFIX):
            deps[key[len(DEP_PREFIX):]] = obj
    return meta, deps


def clear() -> None:
    """Drop the whole journal (serve.shutdown teardown) so the next
    controller starts from a clean slate instead of resurrecting it."""
    _gcs_call("kv_del", {"key": PREFIX, "prefix": True})
