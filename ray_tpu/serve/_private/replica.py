"""Replica actor — hosts one copy of a deployment's user callable.

Reference analogue: serve/_private/replica.py:250 (RayServeReplica,
handle_request:494). Concurrency comes from the actor's thread pool
(``max_concurrency`` = the deployment's ``max_concurrent_queries``);
``num_ongoing_requests`` feeds both router backpressure and the
controller's autoscaling policy.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, Optional, Tuple


class ReplicaActor:
    """Generic wrapper the controller instantiates as an actor."""

    def __init__(self, deployment_name: str, serialized_callable: bytes,
                 init_args: tuple, init_kwargs: dict,
                 user_config: Optional[Any] = None,
                 version: str = ""):
        import cloudpickle
        self.deployment_name = deployment_name
        self.version = version
        fn_or_cls = cloudpickle.loads(serialized_callable)
        if isinstance(fn_or_cls, type):
            self.callable = fn_or_cls(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self.callable = fn_or_cls
            self._is_function = True
        self._ongoing = 0
        self._ongoing_lock = threading.Lock()
        self._total_requests = 0
        self._total_errors = 0
        self._latency_sum = 0.0
        if user_config is not None:
            self.reconfigure(user_config)

    def handle_request(self, method_name: str, args: tuple,
                       kwargs: dict) -> Any:
        t0 = time.time()
        with self._ongoing_lock:
            self._ongoing += 1
            self._total_requests += 1
        try:
            if self._is_function:
                target = self.callable
            else:
                target = getattr(self.callable, method_name or "__call__")
            return target(*args, **kwargs)
        except Exception:
            with self._ongoing_lock:
                self._total_errors += 1
            raise
        finally:
            with self._ongoing_lock:
                self._ongoing -= 1
                self._latency_sum += time.time() - t0

    def reconfigure(self, user_config: Any):
        """Apply a new user_config without restarting the replica
        (reference: replica.py reconfigure path)."""
        if not self._is_function and hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)

    def get_metrics(self) -> Dict[str, Any]:
        with self._ongoing_lock:
            return {
                "num_ongoing_requests": self._ongoing,
                "total_requests": self._total_requests,
                "total_errors": self._total_errors,
                "latency_sum_s": self._latency_sum,
            }

    def check_health(self) -> str:
        """Controller health probe; user callables may define their own
        ``check_health`` raising on failure."""
        if not self._is_function and hasattr(self.callable,
                                             "check_health"):
            self.callable.check_health()
        return "ok"

    def prepare_for_shutdown(self):
        if not self._is_function and hasattr(self.callable, "__del__"):
            try:
                self.callable.__del__()
            except Exception:
                pass
        return "ok"
