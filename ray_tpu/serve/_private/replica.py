"""Replica actor — hosts one copy of a deployment's user callable.

Reference analogue: serve/_private/replica.py:250 (RayServeReplica,
handle_request:494). Concurrency comes from the actor's thread pool;
user-code concurrency is gated by an execution semaphore of
``max_concurrent_queries`` slots, with a bounded waiting room of
``max_queued_requests`` on top. A request arriving past both limits is
shed immediately with a retriable ``ReplicaOverloadedError`` instead of
queueing unboundedly (the router retries it on another replica; the
HTTP proxy maps exhaustion to 503).

The replica also tracks its own load telemetry — queue depth
(executing + waiting) and an EWMA of service time — which the
controller collects into the ``replica_load`` long-poll key for
load-aware routing and autoscaling, and which piggybacks on proxy
responses via ``handle_request_with_load``.

Graceful drain: ``prepare_drain`` flips the replica into draining mode
— it finishes what it has but sheds every NEW arrival with a retriable
``ReplicaOverloadedError`` (routers holding a route table published
before the drain retry on a serving replica). The controller kills a
draining replica only once ``queue_len`` hits zero or the deployment's
``graceful_shutdown_timeout_s`` expires.

Chaos site: ``serve.replica.request`` fires per accepted request
(method = the deployment name), so a seeded schedule can SIGKILL one
replica at an exact request count (``RTPU_CHAOS`` op ``kill``).

Request ids: a caller may tag a request with the reserved
``__rtpu_request_id__`` kwarg (the HTTP proxy maps the ``X-Request-Id``
header onto it). The replica strips it before user code runs, records
(id, outcome, latency) in a bounded per-replica request ledger, and
echoes it in the proxy's response envelope — the join key the game-day
reconciliation pass (``ray_tpu/gameday/reconcile.py``) uses to match
client-observed outcomes against server records. On graceful shutdown
the ledger is flushed to the GCS KV so replicas retired by a rolling
update keep their half of the join.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu._private import chaos, tracing
from ray_tpu.serve.exceptions import ReplicaOverloadedError

# EWMA smoothing for per-request service time: heavy enough to damp
# bimodal request mixes, light enough to track a warmup->steady change
# within ~10 requests.
_EWMA_ALPHA = 0.3

# reserved kwarg carrying a client-supplied request id; stripped before
# the user callable sees kwargs
REQUEST_ID_KWARG = "__rtpu_request_id__"

# reserved kwarg carrying the router's span context ({"trace_id",
# "span_id"}); stripped before user code, parents this replica's
# queue/execute spans (docs/TRACING.md)
TRACE_CTX_KWARG = "__rtpu_trace_ctx__"


def _default_max_queued(max_concurrent_queries: int) -> int:
    env = os.environ.get("RTPU_SERVE_MAX_QUEUED")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return 2 * max_concurrent_queries


def _request_log_max() -> int:
    try:
        return max(0, int(os.environ.get("RTPU_SERVE_REQUEST_LOG_MAX",
                                         65536)))
    except ValueError:
        return 65536


class ReplicaActor:
    """Generic wrapper the controller instantiates as an actor."""

    def __init__(self, deployment_name: str, serialized_callable: bytes,
                 init_args: tuple, init_kwargs: dict,
                 user_config: Optional[Any] = None,
                 version: str = "",
                 max_concurrent_queries: int = 100,
                 max_queued_requests: Optional[int] = None,
                 replica_name: Optional[str] = None):
        import cloudpickle
        self.deployment_name = deployment_name
        self.version = version
        self.replica_name = replica_name or f"{deployment_name}#{os.getpid()}"
        fn_or_cls = cloudpickle.loads(serialized_callable)
        if isinstance(fn_or_cls, type):
            self.callable = fn_or_cls(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self.callable = fn_or_cls
            self._is_function = True
        self._max_concurrent = max(1, int(max_concurrent_queries))
        if max_queued_requests is None:
            max_queued_requests = _default_max_queued(self._max_concurrent)
        self._max_queued = max(0, int(max_queued_requests))
        # user code runs under this semaphore; threads past it wait in
        # the bounded "queued" room counted by admission control below
        self._exec_sem = threading.Semaphore(self._max_concurrent)
        self._draining = False
        self._ongoing = 0
        self._queued = 0
        self._ongoing_lock = threading.Lock()
        self._total_requests = 0
        self._total_errors = 0
        self._total_shed = 0
        self._latency_sum = 0.0
        self._ewma_s = 0.0
        self._have_ewma = False
        # bounded latency reservoir for tail quantiles (p99 in the
        # dashboard serve panel): last 512 service times, O(1) record
        from collections import deque
        self._lat_ring = deque(maxlen=512)
        # per-request ledger: (request_id, outcome, latency_s) for every
        # admitted/shed request — the server half of the game-day
        # reconciliation join. Bounded; overflow is flagged, not silent.
        self._request_log = deque(maxlen=_request_log_max() or 1)
        self._request_log_dropped = 0
        if user_config is not None:
            self.reconfigure(user_config)
        # bucket-prewarm hook: a callable may define __serve_prewarm__
        # (typically calling a @serve.batch method's .prewarm) so every
        # pad bucket compiles at startup instead of on the first unlucky
        # request. Failures must not kill the replica.
        if not self._is_function and hasattr(self.callable,
                                             "__serve_prewarm__"):
            try:
                self.callable.__serve_prewarm__()
            except Exception:
                import traceback
                traceback.print_exc()

    # ---- request path ----

    def handle_request(self, method_name: str, args: tuple,
                       kwargs: dict) -> Any:
        return self._execute(method_name, args, kwargs)

    def handle_request_with_load(self, method_name: str, args: tuple,
                                 kwargs: dict) -> Dict[str, Any]:
        """Proxy path: the result envelope piggybacks this replica's
        current load so the proxy's router sees queue depth at response
        latency, not at the next long-poll tick — and echoes the
        request id so the caller can correlate response to request."""
        rid = kwargs.get(REQUEST_ID_KWARG) if kwargs else None
        result = self._execute(method_name, args, kwargs)
        envelope = {"__serve_result__": result,
                    "__serve_load__": self.get_load()}
        if rid is not None:
            envelope["__serve_request_id__"] = rid
        return envelope

    def _record_request_locked(self, rid: Optional[str], outcome: str,
                               dt: float):
        """Append one ledger entry (caller holds ``_ongoing_lock``)."""
        if len(self._request_log) == self._request_log.maxlen:
            self._request_log_dropped += 1
        self._request_log.append((rid, outcome, round(dt, 6)))

    def _execute(self, method_name: str, args: tuple, kwargs: dict) -> Any:
        t0 = time.monotonic()
        t0_wall = time.time()
        rid = kwargs.pop(REQUEST_ID_KWARG, None) if kwargs else None
        tctx = kwargs.pop(TRACE_CTX_KWARG, None) if kwargs else None
        if chaos._ENGINE is not None:
            # chaos injection point: "kill" at the N-th request this
            # replica accepted (method filter = deployment name)
            chaos.hit("serve.replica.request", self.deployment_name)
        # stream-poll methods a stateful callable lists as drain-exempt
        # (serve/llm: __llm_next__) stay answerable while draining — an
        # in-flight stream must read its remaining tokens before the
        # controller's idle-kill (KV-aware drain, docs/LLM_SERVING.md)
        drain_exempt = (not self._is_function
                        and method_name in getattr(
                            self.callable, "__serve_drain_exempt__", ()))
        with self._ongoing_lock:
            in_flight = self._ongoing + self._queued
            limit = self._max_concurrent + self._max_queued
            if (self._draining and not drain_exempt) \
                    or in_flight >= limit:
                # a draining replica finishes what it has but takes no
                # new work; a full replica sheds — both retriable, so
                # the router re-routes to a replica still in the
                # published table
                self._total_shed += 1
                self._record_request_locked(rid, "shed", 0.0)
                if tctx:
                    tracing.record_span(
                        tctx["trace_id"], tracing.new_span_id(),
                        f"replica.shed:{self.replica_name}",
                        parent_span_id=tctx.get("span_id"),
                        kind="serve.replica", phase="queue",
                        start_ts=t0_wall, end_ts=time.time(),
                        status="shed")
                raise ReplicaOverloadedError(self.deployment_name,
                                             in_flight, limit)
            self._queued += 1
            self._total_requests += 1
        self._exec_sem.acquire()
        with self._ongoing_lock:
            self._queued -= 1
            self._ongoing += 1
        # replica-side spans: the bounded-ingress wait ("queue") then
        # user code ("execute"); the execute span is installed as the
        # worker's current trace ctx so tasks/actor calls the user code
        # makes nest under this request in the trace tree
        exec_span = None
        prev_trace = worker = None
        if tctx and tctx.get("trace_id"):
            t_q = time.time()
            if t_q - t0_wall > 1e-4:  # don't record empty queue waits
                tracing.record_span(
                    tctx["trace_id"], tracing.new_span_id(),
                    f"replica.queue:{self.replica_name}",
                    parent_span_id=tctx.get("span_id"),
                    kind="serve.replica", phase="queue",
                    start_ts=t0_wall, end_ts=t_q)
            exec_span = tracing.span_if(
                tctx["trace_id"], f"replica.execute:{self.replica_name}",
                parent_span_id=tctx.get("span_id"),
                kind="serve.replica", phase="execute",
                attrs={"deployment": self.deployment_name,
                       "method": method_name or "__call__"})
            if exec_span is not None:
                from ray_tpu._private import worker as worker_mod
                worker = worker_mod._global_worker
                if worker is not None:
                    prev_trace = getattr(worker.task_context, "trace",
                                         None)
                    worker.task_context.trace = exec_span.trace_ctx()
        # the disagg prefill hop is an INTERNAL sub-request: the same
        # request id completes again on the decode replica, so ledger
        # it under its own outcome or the per-request reconciliation
        # join would read the pair as a duplicate completion
        outcome = ("prefill" if method_name == "__llm_prefill__"
                   else "ok")
        try:
            if self._is_function:
                target = self.callable
            else:
                target = getattr(self.callable, method_name or "__call__")
            if rid is not None and not self._is_function and getattr(
                    self.callable, "__serve_wants_request_id__", False):
                # stateful callables (serve/llm) opt back into seeing
                # the request id (per-request token ledger, spans)
                kwargs = dict(kwargs, **{REQUEST_ID_KWARG: rid})
            return target(*args, **kwargs)
        except Exception:
            outcome = "error"
            with self._ongoing_lock:
                self._total_errors += 1
            raise
        finally:
            if worker is not None:
                worker.task_context.trace = prev_trace
            if exec_span is not None:
                exec_span.finish(
                    "error" if outcome == "error" else "ok")
            self._exec_sem.release()
            dt = time.monotonic() - t0
            with self._ongoing_lock:
                self._ongoing -= 1
                self._latency_sum += dt
                if self._have_ewma:
                    self._ewma_s += _EWMA_ALPHA * (dt - self._ewma_s)
                else:
                    self._ewma_s, self._have_ewma = dt, True
                self._lat_ring.append(dt)
                self._record_request_locked(rid, outcome, dt)

    # ---- control plane ----

    def reconfigure(self, user_config: Any):
        """Apply a new user_config without restarting the replica
        (reference: replica.py reconfigure path)."""
        if not self._is_function and hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)

    def get_load(self) -> Dict[str, Any]:
        """Cheap telemetry snapshot: what the router's power-of-two-
        choices scoring consumes (piggybacked + long-poll refreshed),
        and what the controller's drain poll watches reach zero.

        A stateful callable (serve/llm) merges its own load via the
        ``__serve_load__`` hook: its in-flight sequences add to
        ``queue_len`` — so the drain poll waits for decodes to finish
        and p2c sees decode pressure — and its ``llm`` metrics ride
        the controller telemetry into the autoscaler + /metrics."""
        with self._ongoing_lock:
            out = {
                "queue_len": self._ongoing + self._queued,
                "ewma_s": self._ewma_s,
                "shed": self._total_shed,
                "draining": self._draining,
                "total_requests": self._total_requests,
                "total_errors": self._total_errors,
                "p99_s": self._quantile(0.99),
                "ts": time.time(),
            }
        if not self._is_function and hasattr(self.callable,
                                             "__serve_load__"):
            try:
                extra = self.callable.__serve_load__() or {}
                out["queue_len"] += int(extra.get("queue_len_extra", 0))
                if extra.get("llm") is not None:
                    out["llm"] = extra["llm"]
            except Exception:
                pass
        return out

    def _quantile(self, q: float) -> float:
        """Tail quantile over the bounded reservoir (caller holds the
        lock or tolerates a racy read — the ring is append-only)."""
        if not self._lat_ring:
            return 0.0
        vals = sorted(self._lat_ring)
        idx = min(len(vals) - 1, int(q * len(vals)))
        return vals[idx]

    def get_llm_state(self) -> Optional[Dict[str, Any]]:
        """LLM engine metrics + token ledger (serve/llm), read OUTSIDE
        the request path — collection must not move the request
        counters the game-day reconciliation compares."""
        if not self._is_function and hasattr(self.callable,
                                             "__llm_metrics__"):
            try:
                return self.callable.__llm_metrics__()
            except Exception:
                return None
        return None

    def get_replica_metadata(self) -> Dict[str, Any]:
        """Identity for controller re-adoption (orphan sweep after a
        controller restart): which deployment + code version this
        replica is running."""
        return {"deployment": self.deployment_name,
                "version": self.version}

    def get_request_log(self) -> Dict[str, Any]:
        """This replica's request ledger: every admitted/shed request
        as (request_id, outcome, latency_s), ``outcome`` in
        ok|error|shed|prefill (``prefill`` = the disagg two-hop's
        internal first hop — admitted work, not a client-visible
        completion). ``truncated`` means the bounded log overflowed
        (raise ``RTPU_SERVE_REQUEST_LOG_MAX``) — per-request joins are
        then unreliable and reconciliation says so."""
        with self._ongoing_lock:
            return {"deployment": self.deployment_name,
                    "replica": self.replica_name,
                    "records": list(self._request_log),
                    "truncated": self._request_log_dropped > 0}

    def prepare_drain(self) -> str:
        """Graceful-drain step 2 (step 1 removed us from the route
        table): stop accepting new requests; in-flight ones finish.
        Stateful callables get the ``__serve_prepare_drain__`` hook so
        their own admission (the LLM engine's) closes too, while their
        in-flight work (decoding sequences) runs to completion."""
        with self._ongoing_lock:
            self._draining = True
        if not self._is_function and hasattr(self.callable,
                                             "__serve_prepare_drain__"):
            try:
                self.callable.__serve_prepare_drain__()
            except Exception:
                import traceback
                traceback.print_exc()
        return "ok"

    def get_metrics(self) -> Dict[str, Any]:
        with self._ongoing_lock:
            return {
                "num_ongoing_requests": self._ongoing,
                "num_queued_requests": self._queued,
                "queue_len": self._ongoing + self._queued,
                "total_requests": self._total_requests,
                "total_errors": self._total_errors,
                "total_shed": self._total_shed,
                "latency_sum_s": self._latency_sum,
                "ewma_service_time_s": self._ewma_s,
                "p50_s": self._quantile(0.50),
                "p99_s": self._quantile(0.99),
                "max_concurrent_queries": self._max_concurrent,
                "max_queued_requests": self._max_queued,
            }

    def check_health(self) -> str:
        """Controller health probe; user callables may define their own
        ``check_health`` raising on failure."""
        if not self._is_function and hasattr(self.callable,
                                             "check_health"):
            self.callable.check_health()
        return "ok"

    def prepare_for_shutdown(self):
        # stateful callables flush their own state first (serve/llm:
        # the per-request token ledger a rolling update must not lose)
        if not self._is_function and hasattr(
                self.callable, "__serve_prepare_shutdown__"):
            try:
                self.callable.__serve_prepare_shutdown__(
                    self.replica_name)
            except Exception:
                pass
        # stop @serve.batch flusher threads: admission is closed and
        # in-flight requests finished, so the queues are drained
        if not self._is_function:
            d = getattr(self.callable, "__dict__", None) or {}
            for attr, v in list(d.items()):
                if attr.startswith("__serve_batcher_"):
                    try:
                        v.stop(timeout_s=1.0)
                    except Exception:
                        pass
        # drain this process's task-event ring synchronously: the
        # controller kills us right after this RPC returns, and the
        # FINISHED events of our last requests (≤0.5 s of batching)
        # must reach the GCS for the state engine to agree with the
        # client ledger (gameday reconciliation check C6)
        try:
            from ray_tpu._private import task_events as tev
            tev.flush_all(timeout=2.0)
        except Exception:
            pass
        # same for the trace-span ring: a replica retired by a rolling
        # update must not take the tail of its request spans with it
        # (the gameday trace-completeness check joins against them)
        try:
            tracing.flush_all(timeout=2.0)
        except Exception:
            pass
        # flush the request ledger before dying: a replica retired by a
        # rolling update / downscale must not take the server half of
        # the game-day reconciliation join with it (best-effort — a KV
        # outage degrades observability, never shutdown)
        try:
            with self._ongoing_lock:
                records = list(self._request_log)
                truncated = self._request_log_dropped > 0
            if records:
                from ray_tpu.gameday import store
                store.flush_replica_ledger(
                    self.replica_name, self.deployment_name,
                    records, truncated=truncated)
        except Exception:
            pass
        if not self._is_function and hasattr(self.callable, "__del__"):
            try:
                self.callable.__del__()
            except Exception:
                pass
        return "ok"
