"""Long-poll config propagation.

Reference analogue: serve/_private/long_poll.py (LongPollHost:185,
LongPollClient:68). The host lives inside the controller actor; clients
issue blocking ``listen`` calls (served on the controller's thread pool)
that return only when the keyed snapshot's version advances — push-like
latency with pull-only plumbing.

Controller HA: versions are per-controller-incarnation. A restarted
controller starts its counters at zero, so a client can legitimately
hold a ``last_version`` AHEAD of the host. The host returns immediately
in that case (instead of parking the regressed client for a full
timeout), and the client treats a version regression as a restart
signal: it resets its cursor and applies the fresh snapshot. While the
controller is down, clients keep their last snapshot (routers/proxy
keep serving from it) and redial the listen with exponential backoff.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


class LongPollHost:
    """Versioned key→snapshot store with blocking listeners."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._versions: Dict[str, int] = {}
        self._snapshots: Dict[str, Any] = {}

    def notify_changed(self, key: str, snapshot: Any):
        with self._cv:
            self._versions[key] = self._versions.get(key, 0) + 1
            self._snapshots[key] = snapshot
            self._cv.notify_all()

    def listen(self, key: str, last_version: int,
               timeout: float = 30.0) -> Tuple[int, Any]:
        """Block until version(key) > last_version (or timeout); returns
        (current_version, snapshot). A ``last_version`` from a previous
        controller incarnation (> current) returns immediately so the
        client can resync instead of stalling a full timeout."""
        # One absolute deadline: notify_all fires for *any* key, so each
        # wakeup must wait only the remaining time, not a fresh `timeout`
        # (otherwise churn on other keys can block far past `timeout`).
        deadline = time.monotonic() + timeout
        with self._cv:
            if last_version > self._versions.get(key, 0):
                return (self._versions.get(key, 0),
                        self._snapshots.get(key))
            while self._versions.get(key, 0) <= last_version:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(timeout=remaining):
                    break
            return (self._versions.get(key, 0),
                    self._snapshots.get(key))

    def get(self, key: str) -> Tuple[int, Any]:
        with self._lock:
            return self._versions.get(key, 0), self._snapshots.get(key)


class LongPollClient:
    """Background thread repeatedly calling ``listen`` on the controller
    and firing callbacks on change. Survives controller restarts: RPC
    failures back off exponentially (the cached snapshot keeps serving),
    and a version regression from a restarted controller resets the
    cursor and re-applies the fresh snapshot."""

    _BACKOFF_MIN_S = 0.2
    _BACKOFF_MAX_S = 5.0

    def __init__(self, controller_handle, key: str,
                 callback: Callable[[Any], None]):
        import ray_tpu
        self._ray = ray_tpu
        self._controller = controller_handle
        self._key = key
        self._callback = callback
        self._version = -1  # -1 so the first listen returns immediately
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        backoff = self._BACKOFF_MIN_S
        while not self._stopped.is_set():
            try:
                version, snapshot = self._ray.get(
                    self._controller.listen_for_change.remote(
                        self._key, self._version), timeout=60.0)
                backoff = self._BACKOFF_MIN_S
            except Exception:
                if self._stopped.is_set():
                    return
                # controller down/restarting: keep the cached snapshot,
                # redial with backoff
                self._stopped.wait(backoff)
                backoff = min(backoff * 2, self._BACKOFF_MAX_S)
                continue
            if version > self._version:
                self._version = version
                try:
                    self._callback(snapshot)
                except Exception:
                    pass
            elif version < self._version:
                # restarted controller: version counters reset. Adopt
                # its cursor; apply its snapshot if it already has one
                # (None = nothing published yet — the next publish will
                # advance past the adopted cursor and fire normally).
                self._version = version
                if snapshot is not None:
                    try:
                        self._callback(snapshot)
                    except Exception:
                        pass

    def stop(self):
        self._stopped.set()
