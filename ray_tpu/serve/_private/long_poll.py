"""Long-poll config propagation.

Reference analogue: serve/_private/long_poll.py (LongPollHost:185,
LongPollClient:68). The host lives inside the controller actor; clients
issue blocking ``listen`` calls (served on the controller's thread pool)
that return only when the keyed snapshot's version advances — push-like
latency with pull-only plumbing.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


class LongPollHost:
    """Versioned key→snapshot store with blocking listeners."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._versions: Dict[str, int] = {}
        self._snapshots: Dict[str, Any] = {}

    def notify_changed(self, key: str, snapshot: Any):
        with self._cv:
            self._versions[key] = self._versions.get(key, 0) + 1
            self._snapshots[key] = snapshot
            self._cv.notify_all()

    def listen(self, key: str, last_version: int,
               timeout: float = 30.0) -> Tuple[int, Any]:
        """Block until version(key) > last_version (or timeout); returns
        (current_version, snapshot)."""
        # One absolute deadline: notify_all fires for *any* key, so each
        # wakeup must wait only the remaining time, not a fresh `timeout`
        # (otherwise churn on other keys can block far past `timeout`).
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._versions.get(key, 0) <= last_version:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(timeout=remaining):
                    break
            return (self._versions.get(key, 0),
                    self._snapshots.get(key))

    def get(self, key: str) -> Tuple[int, Any]:
        with self._lock:
            return self._versions.get(key, 0), self._snapshots.get(key)


class LongPollClient:
    """Background thread repeatedly calling ``listen`` on the controller
    and firing callbacks on change."""

    def __init__(self, controller_handle, key: str,
                 callback: Callable[[Any], None]):
        import ray_tpu
        self._ray = ray_tpu
        self._controller = controller_handle
        self._key = key
        self._callback = callback
        self._version = -1  # -1 so the first listen returns immediately
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stopped.is_set():
            try:
                version, snapshot = self._ray.get(
                    self._controller.listen_for_change.remote(
                        self._key, self._version), timeout=60.0)
            except Exception:
                if self._stopped.is_set():
                    return
                self._stopped.wait(1.0)
                continue
            if version > self._version:
                self._version = version
                try:
                    self._callback(snapshot)
                except Exception:
                    pass

    def stop(self):
        self._stopped.set()
