"""HTTP adapters: payload → model-input conversion at the ingress.

Reference analogue: serve/http_adapters.py (json_to_ndarray,
json_to_multi_ndarray, pandas_read_json, image_to_ndarray,
starlette_request). Design difference: this proxy (http_proxy.py)
decodes the body BEFORE routing — JSON bodies arrive as Python
objects, non-JSON as str — so adapters transform that decoded payload
rather than a raw ASGI request. Compose one with a driver via
``DAGDriver.bind(routes, http_adapter=json_to_ndarray)``.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def starlette_request(payload: Any) -> Any:
    """Identity — hand the decoded payload through unchanged (the
    reference's default)."""
    return payload


def json_request(payload: Any) -> Any:
    """Alias of the default for API parity."""
    return payload


def json_to_ndarray(payload: Any) -> np.ndarray:
    """{"array": [...]} or a bare list → float32 ndarray (reference:
    http_adapters.py json_to_ndarray)."""
    if isinstance(payload, dict):
        if "array" not in payload:
            raise ValueError(
                "json_to_ndarray expects {'array': [...]} "
                f"(got keys {sorted(payload)})")
        payload = payload["array"]
    return np.asarray(payload, dtype=np.float32)


def json_to_multi_ndarray(payload: Any) -> Dict[str, np.ndarray]:
    """{name: nested-list} → {name: ndarray}."""
    if not isinstance(payload, dict):
        raise ValueError("json_to_multi_ndarray expects a JSON object")
    return {k: np.asarray(v, dtype=np.float32)
            for k, v in payload.items()}


def pandas_read_json(payload: Any):
    """JSON records → pandas DataFrame (requires pandas)."""
    import pandas as pd
    if isinstance(payload, str):
        import io
        return pd.read_json(io.StringIO(payload))
    return pd.DataFrame(payload)


def image_to_ndarray(payload: Any) -> np.ndarray:
    """Base64-encoded image bytes → HWC uint8 ndarray (requires PIL;
    reference: http_adapters.py image_to_ndarray)."""
    import base64
    import io
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL not in image
        raise ImportError(
            "image_to_ndarray requires pillow, which is not installed "
            "in this environment") from e
    if isinstance(payload, dict):
        payload = payload.get("image", payload.get("data"))
    data = base64.b64decode(payload)
    return np.asarray(Image.open(io.BytesIO(data)))
