"""ray_tpu.serve — online model serving on actors.

Reference analogue: serve/ (controller/proxy/router/replica, LongPoll,
autoscaling, batching, deployment graphs). JAX-first serving: replicas
host jitted callables; @serve.batch pads to power-of-two buckets so XLA
compiles once per bucket, not per batch size.
"""

from ray_tpu.serve.api import (Application, Deployment, delete,
                               delete_application, deployment,
                               get_deployment_handle, list_applications,
                               metrics, run, shutdown, start, status)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.exceptions import (BatchSubmitTimeoutError,
                                      ReplicaOverloadedError)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.ingress import APIRouter, ingress
from ray_tpu.serve._private.autoscaling import AutoscalingConfig

__all__ = [
    "deployment", "run", "start", "shutdown", "status", "metrics",
    "delete", "delete_application", "list_applications",
    "get_deployment_handle", "Deployment", "Application",
    "DeploymentHandle", "batch", "AutoscalingConfig",
    "APIRouter", "ingress",
    "ReplicaOverloadedError", "BatchSubmitTimeoutError",
    "llm",
]


def __getattr__(name):
    # serve.llm loads lazily: the LLM engine (docs/LLM_SERVING.md)
    # pulls in numpy/jax paths that plain serve users shouldn't pay for
    if name == "llm":
        import importlib
        return importlib.import_module("ray_tpu.serve.llm")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
