"""Request batching — @serve.batch.

Reference analogue: serve/batching.py. TPU-first addition: opt-in
``pad_to_bucket`` pads every flushed batch up to the next power-of-two
bucket so the wrapped JAX callable sees a small fixed set of shapes and
never recompiles per batch size (SURVEY.md §7 "fixed shapes" hard part).

Flush machinery: one background flusher thread per batcher (the old
design armed a ``threading.Timer`` per flush, so every request on an
idle queue paid the full ``batch_wait_timeout_s`` window and each flush
cost a thread spawn). The flusher's wait window adapts to load: an
arrival into an idle queue flushes immediately, and the window grows
toward ``batch_wait_timeout_s`` only while flushes are coming out full
— AIMD on observed batch occupancy. ``adaptive=False`` (or
``RTPU_SERVE_ADAPTIVE_BATCH=0``) restores the fixed window.
"""

from __future__ import annotations

import functools
import os
import threading
from time import monotonic
from typing import Any, Callable, List, Optional

from ray_tpu.serve.exceptions import BatchSubmitTimeoutError


def next_bucket(n: int, max_size: int, min_bucket: int = 1) -> int:
    b = max(1, min_bucket)
    while b < n:
        b *= 2
    return min(b, max_size)


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "no", "")


def _default_submit_timeout() -> float:
    try:
        return float(os.environ.get(
            "RTPU_SERVE_BATCH_SUBMIT_TIMEOUT_S", 60.0))
    except ValueError:
        return 60.0


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float,
                 pad_to_bucket: bool, min_pad_bucket: int = 1,
                 submit_timeout_s: Optional[float] = None,
                 adaptive: Optional[bool] = None):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.pad_to_bucket = pad_to_bucket
        self.min_pad_bucket = max(1, min_pad_bucket)
        self.submit_timeout_s = (submit_timeout_s
                                 if submit_timeout_s is not None
                                 else _default_submit_timeout())
        self.adaptive = (adaptive if adaptive is not None
                         else _env_flag("RTPU_SERVE_ADAPTIVE_BATCH", True))
        self._init_runtime_state()

    def _init_runtime_state(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[dict] = []
        self._window = 0.0  # adaptive wait; 0 = flush idle arrivals now
        self._num_flushes = 0
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._self_obj = None

    def __getstate__(self):
        # batchers ride along when a decorated callable is cloudpickled
        # into a replica: ship the config, rebuild locks/queue/thread
        # fresh on the other side (in-flight entries stay local)
        return {k: getattr(self, k) for k in (
            "fn", "max_batch_size", "batch_wait_timeout_s",
            "pad_to_bucket", "min_pad_bucket", "submit_timeout_s",
            "adaptive")}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._init_runtime_state()

    # ---- submit path ----

    def submit(self, item: Any, self_obj=None) -> Any:
        entry = {"item": item, "event": threading.Event(),
                 "result": None, "error": None}
        with self._cv:
            self._stopping = False
            if self._thread is None:
                # bound instance is fixed per batcher (method batchers
                # are per-instance), so capturing it at first submit is
                # safe and keeps the flusher signature uniform
                self._self_obj = self_obj
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True,
                    name=f"serve-batch-{getattr(self.fn, '__name__', '?')}")
                self._thread.start()
            self._queue.append(entry)
            self._cv.notify_all()
        if not entry["event"].wait(self.submit_timeout_s):
            with self._cv:
                try:
                    self._queue.remove(entry)
                    where = "still queued (flusher wedged?)"
                except ValueError:
                    where = "in flight inside the batch fn"
            # a second chance: the flush may have completed between the
            # wait timeout and the lock
            if not entry["event"].is_set():
                raise BatchSubmitTimeoutError(
                    f"@serve.batch call to "
                    f"{getattr(self.fn, '__name__', self.fn)!r} got no "
                    f"result within submit_timeout_s="
                    f"{self.submit_timeout_s}s — request {where}; raise "
                    f"the timeout via submit_timeout_s= or "
                    f"RTPU_SERVE_BATCH_SUBMIT_TIMEOUT_S if the batch fn "
                    f"is legitimately slow")
        if entry["error"] is not None:
            raise entry["error"]
        return entry["result"]

    # ---- flusher ----

    def _current_window(self) -> float:
        return self._window if self.adaptive else self.batch_wait_timeout_s

    def _adapt(self, batch_len: int):
        """AIMD on occupancy: full flushes grow the window (traffic is
        heavy enough to fill batches — waiting buys occupancy), batches
        under half-full halve it (waiting only added latency). The
        half-full hold band keeps steady near-saturating traffic from
        oscillating between full batches and fragments."""
        if not self.adaptive:
            return
        if batch_len >= self.max_batch_size:
            floor = max(self.batch_wait_timeout_s / 16.0, 1e-4)
            self._window = min(self.batch_wait_timeout_s,
                               max(self._window * 2.0, floor))
        elif batch_len * 2 < self.max_batch_size:
            self._window *= 0.5
            if self._window < 1e-4:
                self._window = 0.0

    def stop(self, timeout_s: float = 5.0):
        """Stop the flusher thread once the queue drains (replica
        teardown). In-flight entries still complete; a later submit
        restarts the flusher."""
        with self._cv:
            t, self._thread = self._thread, None
            self._stopping = True
            self._cv.notify_all()
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout_s)

    def _flush_loop(self):
        while True:
            with self._cv:
                while not self._queue:
                    if self._stopping:
                        return
                    self._cv.wait()
                deadline = monotonic() + self._current_window()
                while len(self._queue) < self.max_batch_size:
                    remaining = deadline - monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                    if not self._queue:  # submit timeouts drained it
                        break
                batch = self._queue[:self.max_batch_size]
                del self._queue[:len(batch)]
                self._adapt(len(batch))
                self._num_flushes += 1
            if batch:
                self._run_batch(batch)
            # anything enqueued while the batch fn ran is still in
            # self._queue — the loop re-arms on it immediately

    def _run_batch(self, batch: List[dict]):
        items = [e["item"] for e in batch]
        n = len(items)
        if self.pad_to_bucket:
            # pad EVERY flush (including singletons) so the callable
            # only ever sees bucket shapes — an unpadded stray size
            # would trigger a fresh JAX compile mid-traffic
            target = next_bucket(n, self.max_batch_size,
                                 self.min_pad_bucket)
            if target > n:
                items = items + [items[-1]] * (target - n)
        try:
            if self._self_obj is not None:
                results = self.fn(self._self_obj, items)
            else:
                results = self.fn(items)
            results = list(results)[:n]
            if len(results) < n:
                raise ValueError(
                    f"batch fn {getattr(self.fn, '__name__', self.fn)!r} "
                    f"returned {len(results)} results for {n} items")
            for e, r in zip(batch, results):
                e["result"] = r
        except Exception as err:
            # every waiter in this flush unblocks with the error —
            # a partially-assigned batch must not strand callers
            for e in batch:
                e["error"] = err
        for e in batch:
            e["event"].set()

    # ---- prewarm ----

    def bucket_sizes(self) -> List[int]:
        sizes = []
        b = self.min_pad_bucket
        while b < self.max_batch_size:
            sizes.append(b)
            b *= 2
        sizes.append(self.max_batch_size)
        return sizes

    def prewarm(self, template_item: Any, self_obj=None):
        """Run the batch fn once per pad bucket so a JAX callable
        compiles every shape it will ever see at startup, instead of on
        the first unlucky request (replicas call this through the
        ``__serve_prewarm__`` hook)."""
        self_obj = self_obj if self_obj is not None else self._self_obj
        for size in (self.bucket_sizes() if self.pad_to_bucket
                     else [self.max_batch_size]):
            items = [template_item] * size
            if self_obj is not None:
                self.fn(self_obj, items)
            else:
                self.fn(items)


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01,
          pad_to_bucket: bool = False,
          min_pad_bucket: int = 1,
          submit_timeout_s: Optional[float] = None,
          adaptive: Optional[bool] = None):
    """Decorate ``fn(list_of_items) -> list_of_results`` (function or
    method); concurrent single-item calls are transparently batched.

    ``adaptive`` (default: env ``RTPU_SERVE_ADAPTIVE_BATCH``, on)
    adapts the flush wait window to load instead of always waiting
    ``batch_wait_timeout_s``. ``submit_timeout_s`` (default: env
    ``RTPU_SERVE_BATCH_SUBMIT_TIMEOUT_S``, 60s) bounds how long one
    call waits on a wedged batch fn. ``min_pad_bucket`` floors the
    ``pad_to_bucket`` bucket set (e.g. 4 → buckets 4, 8, ...).

    The returned wrapper exposes ``.prewarm(item)`` (free functions) /
    ``.prewarm(self, item)`` (methods) to compile every pad bucket
    eagerly."""

    def wrap(fn):
        attr = f"__serve_batcher_{fn.__name__}"

        def make_batcher():
            return _Batcher(fn, max_batch_size, batch_wait_timeout_s,
                            pad_to_bucket, min_pad_bucket,
                            submit_timeout_s, adaptive)

        def get_instance_batcher(self):
            # one batcher PER INSTANCE: a decoration-time batcher would
            # mix items from different instances into one flush
            batcher = getattr(self, attr, None)
            if batcher is None:
                batcher = make_batcher()
                try:
                    setattr(self, attr, batcher)
                except AttributeError:  # __slots__ etc.
                    pass
            return batcher

        @functools.wraps(fn)
        def method_wrapper(self, item):
            return get_instance_batcher(self).submit(item, self_obj=self)

        shared = make_batcher()

        @functools.wraps(fn)
        def fn_wrapper(item):
            return shared.submit(item)

        # heuristically pick method vs free-function form
        import inspect
        params = list(inspect.signature(fn).parameters)
        if params and params[0] == "self":
            wrapper = method_wrapper
            wrapper.prewarm = lambda self, item: \
                get_instance_batcher(self).prewarm(item, self_obj=self)
        else:
            wrapper = fn_wrapper
            wrapper.prewarm = lambda item: shared.prewarm(item)
        wrapper._batcher = shared
        return wrapper

    return wrap if _fn is None else wrap(_fn)
