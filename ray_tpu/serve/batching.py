"""Request batching — @serve.batch.

Reference analogue: serve/batching.py. TPU-first addition: opt-in
``pad_to_bucket`` pads every flushed batch up to the next power-of-two
bucket so the wrapped JAX callable sees a small fixed set of shapes and
never recompiles per batch size (SURVEY.md §7 "fixed shapes" hard part).
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, List, Optional


def next_bucket(n: int, max_size: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, max_size)


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float,
                 pad_to_bucket: bool):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.pad_to_bucket = pad_to_bucket
        self._lock = threading.Lock()
        self._queue: List[dict] = []
        self._flush_timer: Optional[threading.Timer] = None

    def submit(self, item: Any, self_obj=None) -> Any:
        entry = {"item": item, "event": threading.Event(),
                 "result": None, "error": None}
        do_flush = False
        with self._lock:
            self._queue.append(entry)
            if len(self._queue) >= self.max_batch_size:
                do_flush = True
            elif self._flush_timer is None:
                self._flush_timer = threading.Timer(
                    self.batch_wait_timeout_s,
                    lambda: self._flush(self_obj))
                self._flush_timer.daemon = True
                self._flush_timer.start()
        if do_flush:
            self._flush(self_obj)
        entry["event"].wait()
        if entry["error"] is not None:
            raise entry["error"]
        return entry["result"]

    def _flush(self, self_obj=None):
        with self._lock:
            if self._flush_timer is not None:
                self._flush_timer.cancel()
                self._flush_timer = None
            # cap at max_batch_size: late enqueuers between the size check
            # and this lock must not grow the batch past the bucket limit
            batch = self._queue[:self.max_batch_size]
            self._queue = self._queue[self.max_batch_size:]
            if self._queue and self._flush_timer is None:
                self._flush_timer = threading.Timer(
                    self.batch_wait_timeout_s,
                    lambda: self._flush(self_obj))
                self._flush_timer.daemon = True
                self._flush_timer.start()
        if not batch:
            return
        items = [e["item"] for e in batch]
        n = len(items)
        if self.pad_to_bucket and n > 1:
            target = next_bucket(n, self.max_batch_size)
            items = items + [items[-1]] * (target - n)
        try:
            if self_obj is not None:
                results = self.fn(self_obj, items)
            else:
                results = self.fn(items)
            results = list(results)[:n]
            for e, r in zip(batch, results):
                e["result"] = r
        except Exception as err:
            for e in batch:
                e["error"] = err
        for e in batch:
            e["event"].set()


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01,
          pad_to_bucket: bool = False):
    """Decorate ``fn(list_of_items) -> list_of_results`` (function or
    method); concurrent single-item calls are transparently batched."""

    def wrap(fn):
        attr = f"__serve_batcher_{fn.__name__}"

        @functools.wraps(fn)
        def method_wrapper(self, item):
            # one batcher PER INSTANCE: a decoration-time batcher would
            # mix items from different instances into one flush
            batcher = getattr(self, attr, None)
            if batcher is None:
                batcher = _Batcher(fn, max_batch_size,
                                   batch_wait_timeout_s, pad_to_bucket)
                try:
                    setattr(self, attr, batcher)
                except AttributeError:  # __slots__ etc.
                    pass
            return batcher.submit(item, self_obj=self)

        shared = _Batcher(fn, max_batch_size, batch_wait_timeout_s,
                          pad_to_bucket)

        @functools.wraps(fn)
        def fn_wrapper(item):
            return shared.submit(item)

        # heuristically pick method vs free-function form
        import inspect
        params = list(inspect.signature(fn).parameters)
        wrapper = (method_wrapper if params and params[0] == "self"
                   else fn_wrapper)
        wrapper._batcher = shared
        return wrapper

    return wrap if _fn is None else wrap(_fn)
