"""Public Serve API: @serve.deployment, serve.run, handles, status.

Reference analogue: serve/api.py (deployment:251, run:455) and the
Application/bind graph from python/ray/dag. Deployments are pickled
callables shipped to the controller, which reconciles replica actors;
``bind`` composes deployments by injecting DeploymentHandles for bound
upstream deployments (the deployment-graph substrate).
"""

from __future__ import annotations

import time

import cloudpickle
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.handle import (DeploymentHandle, _get_router,
                                  _reset_router)

_DEFAULT_HTTP_PORT = 8000


class Application:
    """A bound deployment DAG rooted at the ingress deployment."""

    def __init__(self, root: "BoundDeployment"):
        self.root = root

    def _collect(self) -> List["BoundDeployment"]:
        seen: Dict[str, BoundDeployment] = {}

        def visit(node: BoundDeployment):
            if node.deployment.name in seen:
                return
            seen[node.deployment.name] = node
            for a in _iter_bindable(list(node.init_args) +
                                    list(node.init_kwargs.values())):
                a = _unwrap(a)
                if isinstance(a, BoundDeployment):
                    visit(a)
        visit(self.root)
        return list(seen.values())


def _iter_bindable(values):
    """Yield candidate bound-deployment leaves, walking one container
    level (DAGDriver takes a {route: bound} dict)."""
    for v in values:
        if isinstance(v, dict):
            yield from v.values()
        elif isinstance(v, (list, tuple)):
            yield from v
        else:
            yield v


class BoundDeployment:
    def __init__(self, deployment: "Deployment", args, kwargs):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs


def _unwrap(x):
    """Applications passed as init args are their root bound node."""
    return x.root if isinstance(x, Application) else x


class Deployment:
    def __init__(self, func_or_class: Union[Callable, type],
                 name: str, config: Dict[str, Any]):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def bind(self, *args, **kwargs) -> Application:
        return Application(BoundDeployment(self, args, kwargs))

    def options(self, **kwargs) -> "Deployment":
        cfg = dict(self.config)
        name = kwargs.pop("name", self.name)
        cfg.update(kwargs)
        return Deployment(self.func_or_class, name, cfg)


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1,
               max_concurrent_queries: int = 100,
               max_queued_requests: Optional[int] = None,
               routing_policy: Optional[str] = None,
               user_config: Optional[Any] = None,
               autoscaling_config: Optional[Dict[str, Any]] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               route_prefix: Optional[str] = None,
               pass_http_path: bool = False,
               graceful_shutdown_timeout_s: Optional[float] = None,
               llm_roles: Optional[Dict[str, int]] = None):
    """@serve.deployment — mark a class/function as a deployment.

    ``max_queued_requests`` bounds each replica's ingress waiting room
    on top of ``max_concurrent_queries`` execution slots (default: env
    ``RTPU_SERVE_MAX_QUEUED``, else 2x max_concurrent_queries); a
    request past both limits is shed with a retriable
    ``ReplicaOverloadedError`` (HTTP 503 at the proxy).
    ``routing_policy`` pins this deployment's replica selection to
    ``"p2c"`` (power-of-two-choices over reported queue depths, the
    default) or ``"round_robin"``; unset defers to ``RTPU_SERVE_ROUTING``.

    ``pass_http_path=True`` makes the HTTP proxy pass the request path
    below the route prefix as a ``__serve_path__`` kwarg — the contract
    driver deployments (drivers.DAGDriver) use to multiplex routes.

    ``graceful_shutdown_timeout_s`` bounds how long a replica leaving
    service (rolling update, downscale, delete, node drain) may keep
    finishing in-flight requests after it is removed from the route
    table, before the controller kills it (default: env
    ``RTPU_SERVE_GRACEFUL_SHUTDOWN_S``, else 10 s).

    ``llm_roles`` (LLM deployments) splits the replicas into prefill
    and decode pools, e.g. ``{"prefill": 1, "decode": 2}``: the
    controller assigns a role per ready replica in the route table and
    the router runs new prompts through a prefill replica before
    streaming from a decode replica, shipping the prompt's KV pages
    between them (docs/LLM_SERVING.md). Unset = every replica unified."""

    def wrap(func_or_class):
        return Deployment(
            func_or_class,
            name or func_or_class.__name__,
            {
                "num_replicas": num_replicas,
                "max_concurrent_queries": max_concurrent_queries,
                "max_queued_requests": max_queued_requests,
                "routing_policy": routing_policy,
                "user_config": user_config,
                "autoscaling_config": autoscaling_config,
                "ray_actor_options": ray_actor_options,
                "route_prefix": route_prefix,
                "graceful_shutdown_timeout_s": graceful_shutdown_timeout_s,
                "llm_roles": llm_roles,
                # @serve.ingress classes (serve/ingress.py) opt into the
                # proxy's path+method passing via class attributes
                "pass_http_path": pass_http_path or bool(getattr(
                    func_or_class, "__serve_pass_http_path__", False)),
                "pass_http_method": bool(getattr(
                    func_or_class, "__serve_pass_http_method__", False)),
            })

    return wrap if _func_or_class is None else wrap(_func_or_class)


def start(http_port: Optional[int] = _DEFAULT_HTTP_PORT,
          http_host: str = "127.0.0.1"):
    """Start (or connect to) the Serve controller; http_port=None means
    no HTTP ingress. An explicit port starts the proxy even when the
    controller already exists.

    The controller runs with ``max_restarts=-1``: when its worker dies,
    the GCS restarts it and the fresh incarnation recovers target state
    from the journal and re-adopts the live replicas (docs/SERVE_HA.md).
    ``start`` therefore tolerates a controller that exists but is
    mid-restart — it waits for the restarted incarnation instead of
    racing a doomed second create against the taken name."""
    from ray_tpu._private import usage as _usage
    _usage.record_library_usage("serve")
    controller = _connect_controller(create=True, http_port=http_port)
    if http_port is not None:
        try:
            proxy = ray_tpu.get_actor("SERVE_PROXY")
            ray_tpu.get(proxy.ping.remote(), timeout=10.0)
        except Exception:
            from ray_tpu.serve.http_proxy import HTTPProxyActor
            proxy_cls = ray_tpu.remote(
                name="SERVE_PROXY", lifetime="detached",
                max_restarts=-1,
                max_concurrency=64)(HTTPProxyActor)
            proxy = proxy_cls.remote(CONTROLLER_NAME, http_host,
                                     http_port)
            ray_tpu.get(proxy.ping.remote(), timeout=30.0)
    return controller


def _connect_controller(create: bool, http_port: Optional[int] = None,
                        timeout: float = 30.0):
    """Resolve a live controller handle, creating one if asked and none
    exists. A controller in RESTARTING is waited on, not replaced."""
    deadline = time.time() + timeout
    last_err: Optional[Exception] = None
    while True:
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            ray_tpu.get(controller.ping.remote(), timeout=10.0)
            return controller
        except Exception as e:
            last_err = e
        if create:
            try:
                controller_cls = ray_tpu.remote(
                    name=CONTROLLER_NAME, lifetime="detached",
                    max_restarts=-1,
                    max_concurrency=32)(ServeController)
                controller = controller_cls.remote(http_port)
                ray_tpu.get(controller.ping.remote(), timeout=30.0)
                return controller
            except Exception as e:
                # lost a create race or the name is held by a
                # RESTARTING incarnation — fall through and re-resolve
                last_err = e
        if time.time() >= deadline:
            raise RuntimeError(
                f"Serve controller unavailable after {timeout}s: "
                f"{type(last_err).__name__}: {last_err}")
        time.sleep(0.5)


def run(app: Union[Application, Deployment], *,
        name: str = "default",
        route_prefix: str = "/",
        http_port: Optional[int] = _DEFAULT_HTTP_PORT,
        _blocking_timeout: float = 60.0) -> DeploymentHandle:
    """Deploy an application; returns a handle to the ingress deployment
    (reference: serve.run serve/api.py:455). ``http_port=None`` runs
    handle-only (no HTTP ingress). ``name`` scopes the app: a redeploy
    replaces only deployments of the same app, so multiple applications
    coexist (reference: multi-app serve.run(name=...))."""
    if isinstance(app, Deployment):
        app = app.bind()
    controller = start(http_port=http_port)
    nodes = app._collect()
    root_name = app.root.deployment.name

    def _to_handle(v):
        u = _unwrap(v)
        if isinstance(u, BoundDeployment):
            return DeploymentHandle(u.deployment.name, controller)
        if isinstance(v, dict):
            return {k: _to_handle(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(_to_handle(x) for x in v)
        return v

    specs = []
    for node in nodes:
        dep = node.deployment
        # bound upstream deployments become handles at init time
        init_args = tuple(_to_handle(a) for a in node.init_args)
        init_kwargs = {k: _to_handle(v)
                       for k, v in node.init_kwargs.items()}
        cfg = dict(dep.config)
        cfg["name"] = dep.name
        cfg["app_name"] = name
        cfg["serialized_callable"] = cloudpickle.dumps(dep.func_or_class)
        cfg["init_args"] = init_args
        cfg["init_kwargs"] = init_kwargs
        if dep.name == root_name and not cfg.get("route_prefix"):
            cfg["route_prefix"] = route_prefix
        specs.append(cfg)
    reply = ray_tpu.get(controller.deploy_application.remote(specs),
                        timeout=60.0)
    if isinstance(reply, dict) and reply.get("error"):
        raise RuntimeError(reply["error"])
    _wait_healthy(controller, [s["name"] for s in specs],
                  timeout=_blocking_timeout)
    if http_port is not None:
        # route barrier: the proxy must be on the post-deploy table
        # before run() returns, or an immediate request can match the
        # previous app's routes (and its torn-down replicas)
        try:
            proxy = ray_tpu.get_actor("SERVE_PROXY")
            ray_tpu.get(proxy.sync_routes.remote(), timeout=30.0)
        except ValueError:
            pass
    return DeploymentHandle(root_name, controller)


def _controller_death_cause(controller) -> Optional[str]:
    """Non-None iff the GCS says the controller actor is DEAD (not
    merely restarting) — the caller should say so instead of timing
    out with a generic 'not healthy' message."""
    try:
        from ray_tpu._private.worker import global_worker
        w = global_worker()
        info = w.call_sync(w.gcs, "get_actor",
                           {"actor_id": controller._id_hex}, timeout=10)
        if info.get("state") == "DEAD":
            return info.get("death_cause") or "unknown cause"
    except Exception:
        pass
    return None


def _wait_healthy(controller, names: List[str], timeout: float):
    deadline = time.time() + timeout
    statuses: Dict[str, Any] = {}
    while time.time() < deadline:
        try:
            statuses = ray_tpu.get(
                controller.get_deployment_statuses.remote(), timeout=30.0)
        except Exception as e:
            cause = _controller_death_cause(controller)
            if cause is not None:
                raise RuntimeError(
                    f"Serve controller has died and will not restart "
                    f"({cause}); deployments {names} cannot converge — "
                    f"run serve.start() / serve.run() to start a new "
                    f"controller") from e
            # transient (controller restarting): retry until deadline
            time.sleep(0.5)
            continue
        if all(statuses.get(n, {}).get("status") == "HEALTHY"
               for n in names):
            return
        time.sleep(0.2)
    cause = _controller_death_cause(controller)
    if cause is not None:
        raise RuntimeError(
            f"Serve controller has died and will not restart ({cause}); "
            f"deployments {names} cannot converge")
    raise TimeoutError(f"deployments {names} not healthy in {timeout}s: "
                       f"{statuses}")


def get_deployment_handle(name: str) -> DeploymentHandle:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return DeploymentHandle(name, controller)


def status() -> Dict[str, Any]:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return ray_tpu.get(
            controller.get_deployment_statuses.remote(), timeout=30.0)
    except Exception:
        return {}


def metrics() -> Dict[str, Any]:
    """Live per-deployment data-plane metrics (queue depth, shed
    total/rate, p99) from the controller's replica_load telemetry —
    what the dashboard serve panel and /metrics render. Empty dict
    when serve isn't running."""
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return ray_tpu.get(
            controller.get_serve_metrics.remote(), timeout=30.0)
    except Exception:
        return {}


def delete(names: Union[str, List[str]]):
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    if isinstance(names, str):
        names = [names]
    ray_tpu.get(controller.delete_deployments.remote(names),
                timeout=30.0)


def delete_application(app_name: str):
    """Tear down one named application (reference: serve.delete)."""
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete_application.remote(app_name),
                timeout=60.0)


def list_applications() -> Dict[str, List[str]]:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return ray_tpu.get(controller.list_applications.remote(),
                           timeout=30.0)
    except Exception:
        return {}


def shutdown():
    """Tear down all deployments, the proxy, and the controller."""
    _reset_router()
    try:
        proxy = ray_tpu.get_actor("SERVE_PROXY")
        try:
            ray_tpu.get(proxy.shutdown.remote(), timeout=10.0)
        except Exception:
            pass
        ray_tpu.kill(proxy)
    except Exception:
        pass
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        try:
            ray_tpu.get(controller.shutdown.remote(), timeout=30.0)
        except Exception:
            pass
        time.sleep(0.5)
        ray_tpu.kill(controller)
    except Exception:
        pass
    # the controller clears its journal on a clean shutdown; if it was
    # already dead, scrub from here so a later serve.start() doesn't
    # resurrect deployments the user just tore down
    try:
        from ray_tpu.serve._private import journal
        journal.clear()
    except Exception:
        pass
