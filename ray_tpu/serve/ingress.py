"""FastAPI-style HTTP ingress for Serve deployments.

Reference analogue: serve/api.py ``@serve.ingress(app)`` — a FastAPI
app mounted on a deployment class so one replica serves the app's whole
route table, with path/query/body params bound to endpoint arguments.

TPU-image redesign: no web-framework dependency. ``APIRouter`` is a
dependency-free router whose route objects expose the same
``path``/``methods``/``endpoint`` surface FastAPI's ``app.routes``
does — so ``ingress()`` accepts either an ``APIRouter`` or a real
FastAPI/Starlette app (duck-typed, endpoints invoked directly) when
one is installed. Dispatch rides the existing proxy contract:
``pass_http_path`` delivers the sub-path and ``pass_http_method`` the
HTTP verb; no second HTTP stack inside replicas.
"""

from __future__ import annotations

import inspect
import re
from typing import Any, Callable, Dict, List, Optional, Tuple


class _Route:
    """Same attribute shape as fastapi.routing.APIRoute."""

    def __init__(self, path: str, methods: List[str], endpoint: Callable):
        self.path = path
        self.methods = set(m.upper() for m in methods)
        self.endpoint = endpoint
        # /items/{item_id} -> ^/items/(?P<item_id>[^/]+)$
        self._regex = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", path) + "$")

    def match(self, path: str) -> Optional[Dict[str, str]]:
        m = self._regex.match(path)
        return m.groupdict() if m else None


class APIRouter:
    """Minimal FastAPI-surface router: ``@app.get("/x/{y}")`` etc.
    Decorating methods inside a class body registers the unbound
    function; ``ingress()`` binds ``self`` at dispatch time (exactly
    the reference's usage pattern)."""

    def __init__(self):
        self.routes: List[_Route] = []

    def _register(self, path: str, methods: List[str]):
        def deco(fn):
            self.routes.append(_Route(path, methods, fn))
            return fn
        return deco

    def get(self, path: str):
        return self._register(path, ["GET"])

    def post(self, path: str):
        return self._register(path, ["POST"])

    def put(self, path: str):
        return self._register(path, ["PUT"])

    def delete(self, path: str):
        return self._register(path, ["DELETE"])

    def route(self, path: str, methods: List[str]):
        return self._register(path, methods)


def _coerce(value: str, annotation: Any) -> Any:
    """Best-effort path/query param coercion from the annotation
    (FastAPI does this via pydantic; int/float/bool cover the common
    cases here)."""
    if annotation in (int, float):
        try:
            return annotation(value)
        except ValueError:
            return value
    if annotation is bool:
        return value.lower() in ("1", "true", "yes")
    return value


def _dispatch(instance, routes, path: str, method: str, payload: Any):
    matched_path = False
    for route in routes:
        params = route.match(path)
        if params is None:
            continue
        matched_path = True
        if method.upper() not in route.methods:
            continue
        fn = route.endpoint
        sig = inspect.signature(fn)
        kwargs: Dict[str, Any] = {}
        args: Tuple = ()
        names = list(sig.parameters)
        if names and names[0] == "self":
            args = (instance,)
            names = names[1:]
        for name in names:
            p = sig.parameters[name]
            if name in params:
                kwargs[name] = _coerce(params[name], p.annotation)
        # query-string payloads arrive as a dict: spread matching keys
        if isinstance(payload, dict):
            for name in names:
                if name not in kwargs and name in payload:
                    kwargs[name] = payload[name]
        # remaining un-filled required param takes the whole body (the
        # FastAPI "body parameter" role) — dict bodies included: a JSON
        # object whose keys didn't fill params by name is still the
        # body (query-style dicts fill everything and leave no leftover)
        leftovers = [n for n in names if n not in kwargs
                     and sig.parameters[n].default is inspect.Parameter.empty]
        if leftovers and payload is not None:
            kwargs[leftovers[0]] = payload
        return fn(*args, **kwargs)
    if matched_path:
        raise LookupError(f"405: method {method} not allowed for {path}")
    raise LookupError(f"404: no ingress route matches {path!r}")


def ingress(app):
    """``@serve.ingress(app)`` — mount an ``APIRouter`` (or FastAPI
    app) on a deployment class. The returned class answers the proxy's
    ``__call__(payload, __serve_path__, __serve_method__)`` contract by
    routing into the app's endpoints with ``self`` bound."""
    def deco(cls):
        # routes are read HERE, not when ingress(app) evaluates:
        # decorator expressions run before the class body, so the
        # @app.get registrations inside the body haven't happened yet
        # at that point. Real FastAPI apps nest non-API routes (docs,
        # openapi); keep only ones with the endpoint surface,
        # normalized into _Route (FastAPI's APIRoute carries the same
        # path/methods/endpoint triple).
        routes = [r if isinstance(r, _Route)
                  else _Route(r.path,
                              list(getattr(r, "methods", ["GET"])),
                              r.endpoint)
                  for r in getattr(app, "routes", ())
                  if hasattr(r, "endpoint") and hasattr(r, "path")]
        class Ingress(cls):
            __serve_pass_http_path__ = True
            __serve_pass_http_method__ = True

            def __call__(self, payload: Any = None,
                         __serve_path__: str = "/",
                         __serve_method__: str = "GET"):
                try:
                    return _dispatch(self, routes, __serve_path__,
                                     __serve_method__, payload)
                except LookupError as e:
                    # routing misses travel as a structured result (the
                    # proxy maps it to the HTTP status) — NOT as an
                    # exception string the proxy would have to sniff
                    msg = str(e)
                    return {"__serve_http_status__":
                            int(msg[:3]) if msg[:3].isdigit() else 500,
                            "error": msg}

        Ingress.__name__ = cls.__name__
        Ingress.__qualname__ = cls.__qualname__
        Ingress.__doc__ = cls.__doc__
        return Ingress

    return deco
