"""DeploymentHandle — composable client for a deployment.

Reference analogue: serve/handle.py:78 (RayServeHandle). ``.remote()``
routes through the shared Router (backpressure-aware) and returns the
underlying ObjectRef; the in-flight slot is released when the ref
completes, so handle callers and the HTTP proxy share one flow-control
mechanism.

Controller HA: a handle pins the controller by ACTOR ID, and the GCS
restarts the controller under the same id (``max_restarts=-1``) — so a
handle serialized before a controller crash deserializes to a working
handle afterwards. ``.remote()`` itself never talks to the controller
once the router has a cached replica set for the deployment, so handle
traffic keeps flowing straight through a controller outage.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

_router_lock = threading.Lock()
_router = None


def _get_router(controller_handle):
    global _router
    with _router_lock:
        if _router is None:
            from ray_tpu.serve._private.router import Router
            _router = Router(controller_handle)
        return _router


def _reset_router():
    global _router
    with _router_lock:
        if _router is not None:
            _router.stop()
        _router = None


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller_handle,
                 method_name: Optional[str] = None):
        self.deployment_name = deployment_name
        self._controller = controller_handle
        self._method_name = method_name

    def options(self, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self.deployment_name, self._controller,
                                method_name)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self._controller,
                                name)

    def remote(self, *args, **kwargs):
        router = _get_router(self._controller)
        ref, release, _replica = router.assign_request(
            self.deployment_name, self._method_name or "__call__",
            args, kwargs)
        # completion callback (no value fetch, no waiter thread); if the
        # ref can't be tracked, release now rather than leak the slot
        if not ref.on_done(release):
            release()
        return ref

    def stream(self, payload=None, *, request_id=None,
               assign_timeout: float = 30.0):
        """Token streaming against an LLM deployment (serve/llm):
        returns a ``ReplicaStream`` — iterate it for incremental chunk
        dicts (``{"tokens", "text", "cursor", "done", ...}``); the
        first chunk arrives as soon as the first token is decoded, not
        when generation completes. Raises ``StreamBrokenError`` if the
        replica dies mid-stream (retry the whole request; partial
        output is never silently passed off as complete)."""
        router = _get_router(self._controller)
        return router.open_stream(self.deployment_name, payload,
                                  request_id=request_id,
                                  assign_timeout=assign_timeout)

    def __repr__(self):
        # stable across processes: the deployment version hash reprs
        # init args, and a memory-address repr would force a full
        # replica replacement on every (identical) redeploy
        return (f"DeploymentHandle({self.deployment_name!r}, "
                f"method={self._method_name!r})")

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self._controller,
                 self._method_name))
