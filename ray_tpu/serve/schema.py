"""Declarative Serve config schema + config-file deployment.

Reference analogue: serve/schema.py (ServeApplicationSchema:258,
DeploymentSchema:124) and `serve deploy` (serve/scripts.py). Apps are
named by import path ("module:app" resolving to an Application or
Deployment); per-deployment overrides apply via .options before
serve.run.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

from pydantic import BaseModel, Field


class DeploymentSchema(BaseModel):
    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    # bounded ingress waiting room + replica-selection policy
    # (docs/SERVE_DATAPLANE.md)
    max_queued_requests: Optional[int] = None
    routing_policy: Optional[str] = None
    user_config: Optional[Any] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    route_prefix: Optional[str] = None
    # drain window for a replica leaving service (docs/SERVE_HA.md)
    graceful_shutdown_timeout_s: Optional[float] = None


class ServeApplicationSchema(BaseModel):
    name: str = "default"
    import_path: str = Field(
        description="module[.sub]:attribute resolving to an Application "
                    "or Deployment")
    route_prefix: str = "/"
    deployments: List[DeploymentSchema] = Field(default_factory=list)
    args: Dict[str, Any] = Field(default_factory=dict)  # builder kwargs


class ServeDeploySchema(BaseModel):
    http_options: Dict[str, Any] = Field(default_factory=dict)
    applications: List[ServeApplicationSchema] = Field(
        default_factory=list)


def import_attr(import_path: str):
    if ":" in import_path:
        module_path, attr = import_path.split(":", 1)
    else:
        module_path, attr = import_path.rsplit(".", 1)
    module = importlib.import_module(module_path)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def build_app(schema: ServeApplicationSchema):
    """Resolve an application schema to a bound Application with
    per-deployment overrides applied."""
    from ray_tpu.serve.api import Application, Deployment
    target = import_attr(schema.import_path)
    if callable(target) and not isinstance(
            target, (Application, Deployment)):
        # app builder function (reference: serve.run target builders)
        target = target(**schema.args)
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(
            f"{schema.import_path} resolved to {type(target).__name__}, "
            "expected an Application, Deployment, or builder")
    overrides = {d.name: d for d in schema.deployments}
    for node in target._collect():
        ov = overrides.get(node.deployment.name)
        if ov is None:
            continue
        cfg = {k: v for k, v in ov.model_dump().items()
               if k != "name" and v is not None}
        node.deployment.config.update(cfg)
    return target


def deploy_config(config: Dict[str, Any],
                  _blocking_timeout: float = 60.0) -> List[str]:
    """Deploy every application in a ServeDeploySchema dict (the payload
    of a config file / REST PUT). Returns the app names deployed."""
    from ray_tpu.serve.api import run
    schema = ServeDeploySchema(**config)
    http_port = schema.http_options.get("port", 8000)
    deployed = []
    for app_schema in schema.applications:
        app = build_app(app_schema)
        run(app, name=app_schema.name,
            route_prefix=app_schema.route_prefix,
            http_port=http_port,
            _blocking_timeout=_blocking_timeout)
        deployed.append(app_schema.name)
    return deployed
