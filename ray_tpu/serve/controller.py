"""ServeController — the Serve control plane actor.

Reference analogue: serve/controller.py:61 (run_control_loop:239,
deploy_app:415) + _private/deployment_state.py (DeploymentState:958,
scaling :1281, rolling updates keyed by version hash). One actor holds
target state, reconciles replica actors toward it in a background
thread, health-checks them, autoscales from queue metrics, and publishes
the route table over long-poll.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _DeploymentInfo:
    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self.version = hashlib.sha1(
            repr(sorted((k, repr(v)) for k, v in config.items()
                        if k != "num_replicas")).encode()).hexdigest()[:12]
        self.target_replicas = config.get("num_replicas", 1)
        # actor handle -> version string
        self.replicas: Dict[Any, str] = {}
        # handles confirmed ready (first successful check_health) —
        # HEALTHY counts these, not mere creations, so serve.run cannot
        # return while replicas are still constructing
        self.ready: set = set()
        self.autoscaler = None
        autoscale = config.get("autoscaling_config")
        if autoscale:
            from ray_tpu.serve._private.autoscaling import (
                AutoscalingConfig, AutoscalingPolicy)
            cfg = (autoscale if isinstance(autoscale, AutoscalingConfig)
                   else AutoscalingConfig(**autoscale))
            self.target_replicas = cfg.min_replicas
            self.autoscaler = AutoscalingPolicy(cfg)


class ServeController:
    """Runs as a named detached actor with a high-concurrency thread
    pool (long-poll listeners block in ``listen_for_change``)."""

    def __init__(self, http_port: Optional[int] = None):
        from ray_tpu.serve._private.long_poll import LongPollHost
        self._deployments: Dict[str, _DeploymentInfo] = {}
        self._lock = threading.RLock()
        self._long_poll = LongPollHost()
        self._replica_seq = 0
        self._shutdown = threading.Event()
        self._http_port = http_port
        self._last_error: Optional[str] = None
        self._last_load_table: Dict[str, Any] = {}
        self._reconcile_thread = threading.Thread(
            target=self._control_loop, daemon=True)
        self._reconcile_thread.start()

    # ---- API called by serve.run / handles ----

    def deploy_application(self, deployments: List[Dict[str, Any]]):
        """Set target state; reconciliation happens asynchronously. Only
        deployments of the SAME app are replaced — apps coexist
        (reference: multi-application serve)."""
        app_name = (deployments[0].get("app_name", "default")
                    if deployments else "default")
        with self._lock:
            new_names = {d["name"] for d in deployments}
            for d in deployments:
                existing = self._deployments.get(d["name"])
                if existing is not None and not \
                        existing.config.get("_deleted") and \
                        existing.config.get("app_name",
                                            "default") != app_name:
                    # a silent takeover would run app B's code under app
                    # A's routes; deployment names are cluster-unique
                    return {"error":
                            f"deployment {d['name']!r} already exists in "
                            f"app {existing.config.get('app_name')!r} — "
                            "deployment names must be unique across apps"}
                info = _DeploymentInfo(d)
                if existing is not None:
                    info.replicas = existing.replicas
                    info.ready = existing.ready
                self._deployments[d["name"]] = info
            same_app = {n for n, i in self._deployments.items()
                        if i.config.get("app_name", "default") == app_name}
            for stale in same_app - new_names:
                self._deployments[stale].target_replicas = 0
                self._deployments[stale].config["_deleted"] = True
        self._reconcile_once()
        return "ok"

    def delete_deployments(self, names: List[str]):
        with self._lock:
            for n in names:
                if n in self._deployments:
                    self._deployments[n].target_replicas = 0
                    self._deployments[n].config["_deleted"] = True
        return "ok"

    def delete_application(self, app_name: str):
        """Tear down every deployment of one app (reference:
        serve.delete(app_name))."""
        with self._lock:
            for n, info in self._deployments.items():
                if info.config.get("app_name", "default") == app_name:
                    info.target_replicas = 0
                    info.config["_deleted"] = True
        self._reconcile_once()
        return "ok"

    def list_applications(self) -> Dict[str, List[str]]:
        with self._lock:
            out: Dict[str, List[str]] = {}
            for n, info in self._deployments.items():
                if info.config.get("_deleted"):
                    continue
                out.setdefault(
                    info.config.get("app_name", "default"), []).append(n)
            return out

    def listen_for_change(self, key: str, last_version: int):
        return self._long_poll.listen(key, last_version)

    def get_route_table(self):
        return self._long_poll.get("route_table")

    def get_deployment_statuses(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out = {}
            for name, info in self._deployments.items():
                if info.config.get("_deleted"):
                    continue
                n_live = sum(1 for h in info.replicas if h in info.ready)
                out[name] = {
                    "name": name,
                    "app": info.config.get("app_name", "default"),
                    "status": ("HEALTHY"
                               if n_live >= info.target_replicas
                               else "UPDATING"),
                    "target_replicas": info.target_replicas,
                    "live_replicas": n_live,
                    "version": info.version,
                }
                if self._last_error:
                    out[name]["last_controller_error"] = self._last_error
            return out

    def get_http_port(self):
        return self._http_port

    def shutdown(self):
        self._shutdown.set()
        with self._lock:
            for info in self._deployments.values():
                info.target_replicas = 0
        self._reconcile_once()
        return "ok"

    def ping(self):
        return "pong"

    # ---- reconciliation ----

    def _control_loop(self):
        import traceback
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
                self._metrics_tick()
                self._health_check()
                self._last_error = None
            except Exception:
                # keep reconciling, but surface the failure in statuses
                self._last_error = traceback.format_exc(limit=8)
            self._shutdown.wait(1.0)

    def _start_replica(self, name: str, info: _DeploymentInfo):
        import ray_tpu
        from ray_tpu.serve._private.replica import ReplicaActor
        cfg = info.config
        self._replica_seq += 1
        mcq = cfg.get("max_concurrent_queries", 100)
        max_queued = cfg.get("max_queued_requests")
        if max_queued is None:
            from ray_tpu.serve._private.replica import _default_max_queued
            max_queued = _default_max_queued(mcq)
        opts = dict(
            name=f"SERVE_REPLICA::{name}#{self._replica_seq}",
            # The actor thread pool must hold executing requests (mcq) +
            # the bounded waiting room (max_queued: threads parked on the
            # replica's execution semaphore) + headroom so a saturated
            # replica still answers check_health/get_load control probes
            # — admission control sheds anything beyond that.
            max_concurrency=mcq + max_queued + 4,
            lifetime="detached",
        )
        if cfg.get("ray_actor_options"):
            opts.update(cfg["ray_actor_options"])
        actor_cls = ray_tpu.remote(**opts)(ReplicaActor)
        h = actor_cls.remote(
            name, cfg["serialized_callable"],
            tuple(cfg.get("init_args") or ()),
            dict(cfg.get("init_kwargs") or {}),
            user_config=cfg.get("user_config"),
            version=info.version,
            max_concurrent_queries=mcq,
            max_queued_requests=max_queued)
        info.replicas[h] = info.version

    def _stop_replica(self, handle):
        import ray_tpu
        try:
            # wait (bounded) for the graceful hook BEFORE killing, else
            # the kill races ahead of the fire-and-forget RPC
            ray_tpu.get(handle.prepare_for_shutdown.remote(),
                        timeout=5.0)
        except Exception:
            pass
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass

    def _reconcile_once(self):
        import ray_tpu
        changed = False
        with self._lock:
            for name, info in list(self._deployments.items()):
                # rolling update: replace replicas on an old version
                stale = [h for h, v in info.replicas.items()
                         if v != info.version]
                for h in stale:
                    self._stop_replica(h)
                    del info.replicas[h]
                    info.ready.discard(h)
                    changed = True
                delta = info.target_replicas - len(info.replicas)
                for _ in range(max(0, delta)):
                    self._start_replica(name, info)
                    changed = True
                for _ in range(max(0, -delta)):
                    h = next(iter(info.replicas))
                    self._stop_replica(h)
                    del info.replicas[h]
                    info.ready.discard(h)
                    changed = True
                if info.config.get("_deleted") and not info.replicas:
                    del self._deployments[name]
                    changed = True
        if changed:
            self._publish_route_table()

    def _publish_route_table(self):
        with self._lock:
            table = {}
            for name, info in self._deployments.items():
                if info.config.get("_deleted"):
                    continue
                table[name] = {
                    "replicas": [h._id_hex
                                 for h in info.replicas],
                    "max_concurrent_queries":
                        info.config.get("max_concurrent_queries", 100),
                    "max_queued_requests":
                        info.config.get("max_queued_requests"),
                    "routing_policy": info.config.get("routing_policy"),
                    "route_prefix": info.config.get("route_prefix"),
                    "pass_http_path":
                        bool(info.config.get("pass_http_path")),
                    "pass_http_method":
                        bool(info.config.get("pass_http_method")),
                }
        self._long_poll.notify_changed("route_table", table)

    def _health_check(self):
        import ray_tpu
        with self._lock:
            items = [(name, info, list(info.replicas))
                     for name, info in self._deployments.items()]
        dead = []
        for name, info, handles in items:
            for h in handles:
                try:
                    ray_tpu.get(h.check_health.remote(), timeout=10.0)
                    if h not in info.ready:
                        with self._lock:
                            info.ready.add(h)
                except Exception:
                    dead.append((info, h))
        if dead:
            with self._lock:
                for info, h in dead:
                    info.replicas.pop(h, None)
                    info.ready.discard(h)
            # routers must stop picking the dead replicas NOW — the next
            # reconcile replaces them, but the table with them removed
            # has to go out immediately
            self._publish_route_table()
            self._reconcile_once()

    def _metrics_tick(self):
        """Collect per-replica load (queue depth incl. the bounded
        waiting room + EWMA service time), publish it on the
        ``replica_load`` long-poll key for load-aware routing, and feed
        the same queue metrics to the autoscaler."""
        import ray_tpu
        now = time.time()
        with self._lock:
            items = [(name, info, list(info.replicas))
                     for name, info in self._deployments.items()
                     if not info.config.get("_deleted")]
        load_table: Dict[str, Dict[str, Any]] = {}
        for name, info, handles in items:
            per_replica = {}
            total_queue = 0.0
            for h in handles:
                try:
                    load = ray_tpu.get(h.get_load.remote(), timeout=5.0)
                    per_replica[h._id_hex] = load
                    total_queue += load.get("queue_len", 0)
                except Exception:
                    # dead/slow replica: the health check owns removal;
                    # routers just won't get a fresh report for it
                    pass
            if per_replica:
                load_table[name] = per_replica
            if info.autoscaler is not None:
                # queue_len (ongoing + queued) — a replica with a full
                # waiting room now registers as load even when its
                # execution slots cap num_ongoing
                decision = info.autoscaler.get_decision(
                    len(handles), total_queue, now)
                if decision != info.target_replicas:
                    with self._lock:
                        info.target_replicas = decision
        if load_table or self._last_load_table:
            self._last_load_table = load_table
            self._long_poll.notify_changed("replica_load", load_table)
