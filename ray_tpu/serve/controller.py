"""ServeController — the Serve control plane actor.

Reference analogue: serve/controller.py:61 (run_control_loop:239,
deploy_app:415) + _private/deployment_state.py (DeploymentState:958,
scaling :1281, rolling updates keyed by version hash). One actor holds
target state, reconciles replica actors toward it in a background
thread, health-checks them, autoscales from queue metrics, and publishes
the route table over long-poll.

Control-plane HA (reference: the controller's KVStore checkpoints +
detached-replica recovery in deployment_state.py):

* **Durable state.** Every mutation of target state (deploy/delete,
  autoscale decisions, replica membership changes) is journaled to the
  GCS KV table (`serve/_private/journal.py`). The controller runs with
  ``max_restarts=-1``; a restarted controller rebuilds ``_deployments``
  from the journal, re-adopts the live detached ``SERVE_REPLICA::*``
  actors by name (replicas are NOT restarted), republishes the route
  table, and resumes reconciliation. Routers and the HTTP proxy keep
  serving from their cached route tables during the outage and
  reconnect their long-polls with backoff.
* **Health-gated rolling updates.** Replicas on a stale version are
  replaced start-before-stop in bounded surge waves
  (``RTPU_SERVE_MAX_SURGE`` extra replicas at a time): each wave's new
  replicas must pass health checks before an old replica is drained.
  A new version that never becomes healthy leaves the old version
  serving.
* **Graceful drain.** A replica leaving service (rolling update,
  downscale, deployment delete, draining node) is first removed from
  the published route table and told to shed new arrivals
  (``prepare_drain``), then killed only once its in-flight count hits
  zero or ``graceful_shutdown_timeout_s`` expires.
* **Node preemption.** Replicas on a node the GCS marks draining are
  condemned: replacements start elsewhere first (the scheduler already
  excludes draining nodes), then the condemned replicas drain inside
  the node's grace window.

Chaos sites (``_private/chaos.py``): ``serve.controller.tick`` fires
once per control-loop iteration (op ``kill`` SIGKILLs the controller
worker — the GCS restarts it); ``serve.replica.request`` lives in the
replica (see ``_private/replica.py``).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.serve.controller")

CONTROLLER_NAME = "SERVE_CONTROLLER"
REPLICA_NAME_PREFIX = "SERVE_REPLICA::"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _max_surge() -> int:
    """Extra replicas a rolling update may run beyond target while a
    wave's new replicas come up (reference: maxSurge in k8s rollouts)."""
    return max(1, _env_int("RTPU_SERVE_MAX_SURGE", 1))


def _health_failure_threshold() -> int:
    return max(1, _env_int("RTPU_SERVE_HEALTH_FAILURES", 3))


def _health_timeout_s() -> float:
    return _env_float("RTPU_SERVE_HEALTH_TIMEOUT_S", 10.0)


def _aggregate_llm(per_replica: Dict[str, Any],
                   roles: Optional[Dict[str, str]] = None
                   ) -> Optional[Dict[str, Any]]:
    """Fold the per-replica ``llm`` load rows (serve/llm engine
    telemetry riding ``ReplicaActor.get_load``) into one deployment-
    level signal set: summed throughput/sequence counts, MEAN KV
    occupancy (each replica owns an equal pool). None when no replica
    reports LLM metrics (stateless deployments stay on queue depth).

    ``roles`` (disaggregated deployments: replica id hex -> role) adds
    a ``per_role`` breakdown so the autoscaler can see prefill and
    decode pressure separately (docs/LLM_SERVING.md)."""
    keyed = [(k, v["llm"]) for k, v in per_replica.items()
             if isinstance(v, dict) and isinstance(v.get("llm"), dict)]
    rows = [r for _, r in keyed]
    if not rows:
        return None
    n = len(rows)
    out = {
        "tokens_per_s": sum(r.get("tokens_per_s", 0.0) for r in rows),
        "cache_hit_tokens_per_s": sum(
            r.get("cache_hit_tokens_per_s", 0.0) for r in rows),
        "cache_hit_tokens_total": sum(
            r.get("cache_hit_tokens_total", 0) for r in rows),
        "kv_occupancy": sum(r.get("kv_occupancy", 0.0)
                            for r in rows) / n,
        "running": sum(r.get("running", 0) for r in rows),
        "waiting": sum(r.get("waiting", 0) for r in rows),
        "generated_tokens_total": sum(
            r.get("generated_tokens_total", 0) for r in rows),
        "finished_total": sum(r.get("finished_total", 0)
                              for r in rows),
        "kv_blocks_used": sum(r.get("kv_blocks_used", 0)
                              for r in rows),
        "kv_blocks_total": sum(r.get("kv_blocks_total", 0)
                               for r in rows),
        "ttft_p99_s": max((r.get("ttft_p99_s", 0.0) for r in rows),
                          default=0.0),
        "replicas_reporting": n,
    }
    if roles:
        per_role: Dict[str, Dict[str, Any]] = {}
        for hex_id, r in keyed:
            role = roles.get(hex_id, "unified")
            agg = per_role.setdefault(role, {
                "tokens_per_s": 0.0, "running": 0, "waiting": 0,
                "kv_occupancy": 0.0, "replicas": 0})
            agg["tokens_per_s"] += r.get("tokens_per_s", 0.0)
            agg["running"] += r.get("running", 0)
            agg["waiting"] += r.get("waiting", 0)
            agg["kv_occupancy"] += r.get("kv_occupancy", 0.0)
            agg["replicas"] += 1
        for agg in per_role.values():
            if agg["replicas"]:
                agg["kv_occupancy"] /= agg["replicas"]
        out["per_role"] = per_role
    return out


class _DeploymentInfo:
    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self.version = hashlib.sha1(
            repr(sorted((k, repr(v)) for k, v in config.items()
                        if k != "num_replicas")).encode()).hexdigest()[:12]
        self.target_replicas = config.get("num_replicas", 1)
        # actor handle -> version string
        self.replicas: Dict[Any, str] = {}
        # handles confirmed ready (first successful check_health) —
        # HEALTHY counts these, not mere creations, so serve.run cannot
        # return while replicas are still constructing
        self.ready: set = set()
        # replica id hex -> detached actor name (journaled so a
        # restarted controller can re-adopt by name)
        self.replica_names: Dict[str, str] = {}
        # handle -> {"deadline": unix, "reason": str}: out of the
        # published route table, killed once idle or at deadline
        self.draining: Dict[Any, Dict[str, Any]] = {}
        # replicas on a draining node — replaced start-before-stop like
        # stale versions, then drained inside the node's grace window
        self.condemned: set = set()
        # replica id hex -> consecutive failed health probes
        self.health_fails: Dict[str, int] = {}
        self.autoscaler = None
        autoscale = config.get("autoscaling_config")
        if autoscale:
            from ray_tpu.serve._private.autoscaling import (
                AutoscalingConfig, AutoscalingPolicy)
            cfg = (autoscale if isinstance(autoscale, AutoscalingConfig)
                   else AutoscalingConfig(**autoscale))
            self.target_replicas = cfg.min_replicas
            self.autoscaler = AutoscalingPolicy(cfg)

    def graceful_timeout_s(self) -> float:
        v = self.config.get("graceful_shutdown_timeout_s")
        if v is None:
            return _env_float("RTPU_SERVE_GRACEFUL_SHUTDOWN_S", 10.0)
        return float(v)

    def carry_over(self, prev: "_DeploymentInfo"):
        """Redeploy: adopt the predecessor's live state (same dict
        objects — in-flight drain polls hold references to them)."""
        self.replicas = prev.replicas
        self.ready = prev.ready
        self.replica_names = prev.replica_names
        self.draining = prev.draining
        self.condemned = prev.condemned
        self.health_fails = prev.health_fails


class ServeController:
    """Runs as a named detached actor (``max_restarts=-1``) with a
    high-concurrency thread pool (long-poll listeners block in
    ``listen_for_change``)."""

    def __init__(self, http_port: Optional[int] = None):
        from ray_tpu.serve._private.long_poll import LongPollHost
        self._deployments: Dict[str, _DeploymentInfo] = {}
        self._lock = threading.RLock()
        self._long_poll = LongPollHost()
        self._replica_seq = 0
        self._journaled_seq = -1
        self._shutdown = threading.Event()
        self._http_port = http_port
        self._last_error: Optional[str] = None   # control-loop level
        self._last_errors: Dict[str, str] = {}   # per-deployment
        self._last_load_table: Dict[str, Any] = {}
        # shed-rate window: deployment -> (ts, total_shed) at the
        # previous metrics read (get_serve_metrics computes sheds/s)
        self._shed_prev: Dict[str, Any] = {}
        self._last_published_table: Optional[Dict[str, Any]] = None
        self._replica_nodes: Dict[str, str] = {}  # replica hex -> node id
        self._draining_nodes: Dict[str, float] = {}  # node id -> deadline
        self._recovered = False
        self._adopted = 0
        self._recover_from_journal()
        self._reconcile_thread = threading.Thread(
            target=self._control_loop, daemon=True)
        self._reconcile_thread.start()

    # ---- journal + recovery ----

    def _journal_meta(self):
        if self._replica_seq == self._journaled_seq:
            return
        from ray_tpu.serve._private import journal
        try:
            journal.put_meta({"replica_seq": self._replica_seq,
                              "namespace": self._namespace()})
            self._journaled_seq = self._replica_seq
        except Exception:
            logger.warning("serve journal: meta write failed",
                           exc_info=True)

    def _journal_deployment(self, name: str):
        """Write one deployment's target state + replica membership.
        Caller holds the lock. Best-effort: a journal outage must not
        take down serving."""
        from ray_tpu.serve._private import journal
        info = self._deployments.get(name)
        try:
            if info is None:
                journal.delete_deployment(name)
                return
            journal.put_deployment(name, {
                "config": info.config,
                "version": info.version,
                "target_replicas": info.target_replicas,
                "replicas": [
                    {"name": info.replica_names.get(h._id_hex, ""),
                     "id": h._id_hex,
                     "version": v,
                     "draining": h in info.draining}
                    for h, v in info.replicas.items()],
            })
        except Exception:
            logger.warning("serve journal: write failed for %r", name,
                           exc_info=True)

    def _namespace(self) -> str:
        try:
            from ray_tpu._private.worker import global_worker
            return global_worker().namespace
        except Exception:
            return ""

    def _recover_from_journal(self):
        """Rebuild ``_deployments`` from the GCS journal and re-adopt
        the live detached replica actors by name — the data plane keeps
        its processes (and its in-flight requests) across a controller
        restart."""
        from ray_tpu.serve._private import journal
        try:
            meta, deps = journal.load_all()
        except Exception:
            logger.warning("serve journal: recovery read failed; "
                           "starting with empty state", exc_info=True)
            return
        if meta:
            self._replica_seq = max(self._replica_seq,
                                    int(meta.get("replica_seq", 0)))
        if not deps:
            return
        ns = (meta or {}).get("namespace", self._namespace())
        now = time.time()
        for name, rec in deps.items():
            try:
                info = _DeploymentInfo(rec["config"])
                info.target_replicas = int(
                    rec.get("target_replicas", info.target_replicas))
                for rep in rec.get("replicas", []):
                    h = self._readopt_replica(rep, ns)
                    if h is None:
                        continue
                    info.replicas[h] = rep.get("version", info.version)
                    info.replica_names[h._id_hex] = rep["name"]
                    self._bump_seq_past(rep["name"])
                    if rep.get("draining"):
                        # resume the interrupted drain with a fresh
                        # grace window
                        info.draining[h] = {
                            "deadline": now + info.graceful_timeout_s(),
                            "reason": "drain resumed after controller "
                                      "restart"}
                        try:
                            h.prepare_drain.remote()
                        except Exception:
                            logger.debug(
                                "serve: prepare_drain to resumed-"
                                "draining replica %s failed (dead? "
                                "health check removes it)",
                                rep["name"], exc_info=True)
                    else:
                        # it was serving a moment ago; health checks
                        # will demote it if that changed
                        info.ready.add(h)
                    self._adopted += 1
                self._deployments[name] = info
            except Exception:
                logger.warning("serve journal: skipping unrecoverable "
                               "deployment %r", name, exc_info=True)
        self._adopt_orphans(ns)
        self._recovered = True
        self._publish_route_table(force=True)
        logger.info("serve controller recovered from journal: "
                    "%d deployments, %d replicas re-adopted",
                    len(self._deployments), self._adopted)

    def _readopt_replica(self, rep: Dict[str, Any], namespace: str):
        """Name -> live ActorHandle, or None if the replica is gone
        (the reconcile loop will start a replacement)."""
        name = rep.get("name")
        if not name:
            return None
        try:
            from ray_tpu._private.worker import global_worker
            from ray_tpu.actor import ActorHandle
            from ray_tpu.common.ids import ActorID
            w = global_worker()
            info = w.call_sync(w.gcs, "get_named_actor",
                               {"name": name, "namespace": namespace},
                               timeout=10)
            if info.get("error") or info.get("state") != "ALIVE":
                return None
            h = ActorHandle(ActorID.from_hex(info["actor_id"]),
                            info.get("class_name", ""))
            if info.get("worker_address"):
                h._worker_address = info["worker_address"]
            if info.get("node_id"):
                self._replica_nodes[h._id_hex] = info["node_id"]
            return h
        except Exception:
            logger.warning("serve journal: re-adopt of %r failed", name,
                           exc_info=True)
            return None

    def _bump_seq_past(self, replica_name: str):
        """Never reuse a live replica's name: advance the sequence past
        any adopted ``...#<seq>`` suffix (covers a journal meta write
        lost right before the crash)."""
        _, _, seq = replica_name.rpartition("#")
        try:
            self._replica_seq = max(self._replica_seq, int(seq))
        except ValueError:
            pass

    def _adopt_orphans(self, namespace: str):
        """A crash between replica creation and the journal write leaks
        a live detached replica the journal doesn't know. Sweep the
        actor directory for ``SERVE_REPLICA::*`` names we don't track:
        adopt the ones whose deployment still exists, kill the rest."""
        import ray_tpu
        try:
            from ray_tpu._private.worker import global_worker
            w = global_worker()
            named = w.call_sync(w.gcs, "list_named_actors",
                                {"namespace": namespace}, timeout=10)
        except Exception:
            return
        tracked = set()
        for info in self._deployments.values():
            tracked.update(info.replica_names.values())
        for entry in named or []:
            name = entry.get("name", "")
            if not name.startswith(REPLICA_NAME_PREFIX) or name in tracked:
                continue
            dep_name = name[len(REPLICA_NAME_PREFIX):].rpartition("#")[0]
            h = self._readopt_replica({"name": name}, namespace)
            if h is None:
                continue
            self._bump_seq_past(name)
            info = self._deployments.get(dep_name)
            version = None
            try:
                meta = ray_tpu.get(h.get_replica_metadata.remote(),
                                   timeout=10.0)
                version = meta.get("version")
            except Exception:
                logger.debug("serve: no metadata from readopted "
                             "replica %r; assuming current version",
                             name, exc_info=True)
            if info is None:
                logger.warning("serve: killing orphan replica %r "
                               "(deployment gone)", name)
                try:
                    ray_tpu.kill(h)
                except Exception:
                    logger.debug("serve: kill of orphan replica %r "
                                 "failed (already gone?)", name,
                                 exc_info=True)
                continue
            info.replicas[h] = version or info.version
            info.replica_names[h._id_hex] = name
            self._adopted += 1

    # ---- API called by serve.run / handles ----

    def deploy_application(self, deployments: List[Dict[str, Any]]):
        """Set target state; reconciliation happens asynchronously. Only
        deployments of the SAME app are replaced — apps coexist
        (reference: multi-application serve)."""
        app_name = (deployments[0].get("app_name", "default")
                    if deployments else "default")
        with self._lock:
            new_names = {d["name"] for d in deployments}
            for d in deployments:
                existing = self._deployments.get(d["name"])
                if existing is not None and not \
                        existing.config.get("_deleted") and \
                        existing.config.get("app_name",
                                            "default") != app_name:
                    # a silent takeover would run app B's code under app
                    # A's routes; deployment names are cluster-unique
                    return {"error":
                            f"deployment {d['name']!r} already exists in "
                            f"app {existing.config.get('app_name')!r} — "
                            "deployment names must be unique across apps"}
                info = _DeploymentInfo(d)
                if existing is not None:
                    info.carry_over(existing)
                self._deployments[d["name"]] = info
                self._journal_deployment(d["name"])
            same_app = {n for n, i in self._deployments.items()
                        if i.config.get("app_name", "default") == app_name}
            for stale in same_app - new_names:
                self._deployments[stale].target_replicas = 0
                self._deployments[stale].config["_deleted"] = True
                self._journal_deployment(stale)
        self._reconcile_once()
        return "ok"

    def delete_deployments(self, names: List[str]):
        with self._lock:
            for n in names:
                if n in self._deployments:
                    self._deployments[n].target_replicas = 0
                    self._deployments[n].config["_deleted"] = True
                    self._journal_deployment(n)
        return "ok"

    def delete_application(self, app_name: str):
        """Tear down every deployment of one app (reference:
        serve.delete(app_name))."""
        with self._lock:
            for n, info in self._deployments.items():
                if info.config.get("app_name", "default") == app_name:
                    info.target_replicas = 0
                    info.config["_deleted"] = True
                    self._journal_deployment(n)
        self._reconcile_once()
        return "ok"

    def list_applications(self) -> Dict[str, List[str]]:
        with self._lock:
            out: Dict[str, List[str]] = {}
            for n, info in self._deployments.items():
                if info.config.get("_deleted"):
                    continue
                out.setdefault(
                    info.config.get("app_name", "default"), []).append(n)
            return out

    def listen_for_change(self, key: str, last_version: int):
        return self._long_poll.listen(key, last_version)

    def get_route_table(self):
        return self._long_poll.get("route_table")

    def get_deployment_statuses(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out = {}
            for name, info in self._deployments.items():
                if info.config.get("_deleted"):
                    continue
                live = sum(1 for h, v in info.replicas.items()
                           if v == info.version and h in info.ready
                           and h not in info.draining)
                stale = sum(1 for h, v in info.replicas.items()
                            if h not in info.draining
                            and (v != info.version or h in info.condemned))
                out[name] = {
                    "name": name,
                    "app": info.config.get("app_name", "default"),
                    # HEALTHY = the TARGET version is fully serving: a
                    # mid-rollout deployment (old version still in the
                    # table) reports UPDATING even though traffic flows
                    "status": ("HEALTHY"
                               if live >= info.target_replicas
                               and stale == 0
                               else "UPDATING"),
                    "target_replicas": info.target_replicas,
                    "live_replicas": live,
                    "stale_replicas": stale,
                    "draining_replicas": len(info.draining),
                    "version": info.version,
                }
                # scoped: only the deployment whose reconcile/health
                # pass errored carries the message
                if name in self._last_errors:
                    out[name]["last_controller_error"] = \
                        self._last_errors[name]
            return out

    def get_serve_metrics(self) -> Dict[str, Any]:
        """Live per-deployment data-plane metrics for the dashboard /
        Prometheus: queue depth (sum over replicas), shed totals +
        shed rate since the previous read, p99/EWMA service time —
        all from the ``replica_load`` telemetry the controller already
        collects every metrics tick (no extra replica RPCs here)."""
        statuses = self.get_deployment_statuses()
        now = time.time()
        out: Dict[str, Any] = {}
        for name, st in statuses.items():
            loads = (self._last_load_table or {}).get(name, {})
            queue_len = sum(v.get("queue_len", 0) for v in loads.values())
            shed_total = sum(v.get("shed", 0) for v in loads.values())
            requests_total = sum(v.get("total_requests", 0)
                                 for v in loads.values())
            errors_total = sum(v.get("total_errors", 0)
                               for v in loads.values())
            p99 = max((v.get("p99_s", 0.0) for v in loads.values()),
                      default=0.0)
            ewma = max((v.get("ewma_s", 0.0) for v in loads.values()),
                       default=0.0)
            prev = self._shed_prev.get(name)
            shed_rate = 0.0
            if prev and now > prev[0]:
                shed_rate = max(0.0, (shed_total - prev[1])
                                / (now - prev[0]))
            self._shed_prev[name] = (now, shed_total)
            out[name] = {
                "app": st.get("app"),
                "status": st.get("status"),
                "replicas": st.get("live_replicas"),
                "target_replicas": st.get("target_replicas"),
                "queue_len": queue_len,
                "shed_total": shed_total,
                "shed_rate_per_s": round(shed_rate, 3),
                "requests_total": requests_total,
                "errors_total": errors_total,
                "p99_s": round(p99, 6),
                "ewma_s": round(ewma, 6),
            }
            llm = _aggregate_llm(loads)
            if llm is not None:
                out[name]["llm"] = llm
        return out

    def get_controller_info(self) -> Dict[str, Any]:
        """Introspection for tests/bench/ops: restart identity, journal
        recovery outcome, and loop-level (non-deployment) errors."""
        return {
            "pid": os.getpid(),
            "recovered": self._recovered,
            "adopted_replicas": self._adopted,
            "replica_seq": self._replica_seq,
            "last_error": self._last_error,
        }

    def get_http_port(self):
        return self._http_port

    def shutdown(self):
        """Explicit teardown (serve.shutdown): fast-stop every replica —
        graceful drain is for keeping traffic alive, and there is no
        traffic to keep alive after an intentional full teardown."""
        from ray_tpu.serve._private import journal
        self._shutdown.set()
        with self._lock:
            handles = []
            for info in self._deployments.values():
                info.target_replicas = 0
                handles.extend(info.replicas)
                info.replicas = {}
                info.draining = {}
                info.ready = set()
            self._deployments = {}
        for h in handles:
            self._stop_replica(h)
        try:
            journal.clear()
        except Exception:
            logger.warning("serve journal: clear on shutdown failed",
                           exc_info=True)
        self._publish_route_table(force=True)
        return "ok"

    def ping(self):
        return "pong"

    # ---- reconciliation ----

    def _control_loop(self):
        from ray_tpu._private import chaos
        while not self._shutdown.is_set():
            try:
                if chaos._ENGINE is not None:
                    # op "kill" = SIGKILL this worker; the GCS actor
                    # state machine restarts the controller, which
                    # recovers from the journal
                    chaos.hit("serve.controller.tick")
                self._refresh_node_view()
                self._reconcile_once()
                self._metrics_tick()
                self._health_check()
                self._last_error = None
            except Exception:
                # keep reconciling, but surface the failure
                self._last_error = traceback.format_exc(limit=8)
            self._shutdown.wait(1.0)

    def _refresh_node_view(self):
        """Draining-node set from the GCS node table: replicas living
        there must be replaced before the grace window closes."""
        try:
            from ray_tpu._private.worker import global_worker
            w = global_worker()
            nodes = w.call_sync(w.gcs, "get_nodes", {}, timeout=5)
        except Exception:
            return  # keep the previous view
        self._draining_nodes = {
            n["node_id"]: float(n.get("drain_deadline_unix") or 0.0)
            for n in nodes
            if n.get("alive") and n.get("draining")}

    def _start_replica(self, name: str, info: _DeploymentInfo):
        import ray_tpu
        from ray_tpu.serve._private.replica import ReplicaActor
        cfg = info.config
        self._replica_seq += 1
        mcq = cfg.get("max_concurrent_queries", 100)
        max_queued = cfg.get("max_queued_requests")
        if max_queued is None:
            from ray_tpu.serve._private.replica import _default_max_queued
            max_queued = _default_max_queued(mcq)
        replica_name = f"{REPLICA_NAME_PREFIX}{name}#{self._replica_seq}"
        opts = dict(
            name=replica_name,
            # The actor thread pool must hold executing requests (mcq) +
            # the bounded waiting room (max_queued: threads parked on the
            # replica's execution semaphore) + headroom so a saturated
            # replica still answers check_health/get_load control probes
            # — admission control sheds anything beyond that.
            max_concurrency=mcq + max_queued + 4,
            lifetime="detached",
        )
        if cfg.get("ray_actor_options"):
            opts.update(cfg["ray_actor_options"])
        actor_cls = ray_tpu.remote(**opts)(ReplicaActor)
        h = actor_cls.remote(
            name, cfg["serialized_callable"],
            tuple(cfg.get("init_args") or ()),
            dict(cfg.get("init_kwargs") or {}),
            user_config=cfg.get("user_config"),
            version=info.version,
            max_concurrent_queries=mcq,
            max_queued_requests=max_queued,
            replica_name=replica_name)
        info.replicas[h] = info.version
        info.replica_names[h._id_hex] = replica_name
        return h

    def _stop_replica(self, handle):
        import ray_tpu
        try:
            # wait (bounded) for the graceful hook BEFORE killing, else
            # the kill races ahead of the fire-and-forget RPC
            ray_tpu.get(handle.prepare_for_shutdown.remote(),
                        timeout=5.0)
        except Exception:
            pass
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass

    def _forget_replica(self, info: _DeploymentInfo, h):
        """Drop every trace of a replica from one deployment's state.
        Caller holds the lock."""
        info.replicas.pop(h, None)
        info.ready.discard(h)
        info.draining.pop(h, None)
        info.condemned.discard(h)
        info.health_fails.pop(h._id_hex, None)
        info.replica_names.pop(h._id_hex, None)
        self._replica_nodes.pop(h._id_hex, None)

    def _least_loaded(self, name: str, handles) -> List[Any]:
        """Sort by last-reported queue depth ascending (the downscale /
        drain victim order) — never evict the busiest replica when a
        quieter one frees the same capacity."""
        loads = self._last_load_table.get(name, {})

        def key(h):
            rep = loads.get(h._id_hex) or {}
            return (float(rep.get("queue_len", 0.0)), h._id_hex)

        return sorted(handles, key=key)

    def _lookup_replica_node(self, h) -> Optional[str]:
        nid = self._replica_nodes.get(h._id_hex)
        if nid is not None:
            return nid
        try:
            from ray_tpu._private.worker import global_worker
            w = global_worker()
            info = w.call_sync(w.gcs, "get_actor",
                               {"actor_id": h._id_hex}, timeout=5)
            nid = info.get("node_id")
            if nid:
                self._replica_nodes[h._id_hex] = nid
            return nid
        except Exception:
            return None

    def _begin_drain(self, name: str, info: _DeploymentInfo, h,
                     reason: str):
        """Take a replica out of service WITHOUT dropping its work:
        remove it from the published table (caller republishes), tell
        it to shed new arrivals retriably, and schedule the kill for
        when it is idle (bounded by graceful_shutdown_timeout_s)."""
        now = time.time()
        deadline = now + info.graceful_timeout_s()
        if h in info.condemned:
            # finish before the node's own grace window slams shut
            node_dl = self._draining_nodes.get(
                self._replica_nodes.get(h._id_hex) or "", 0.0)
            if node_dl:
                deadline = min(deadline, max(now, node_dl - 1.0))
        # "notified" stays False for one propagation window: the route
        # table WITHOUT this replica must reach routers before the
        # replica starts shedding stragglers (else requests assigned in
        # the window surface errors instead of landing elsewhere)
        info.draining[h] = {"deadline": deadline, "begun": now,
                            "notified": False, "reason": reason}
        info.ready.discard(h)
        logger.info("serve: draining replica %s of %r (%s)",
                    info.replica_names.get(h._id_hex,
                                           h._id_hex[:8]), name, reason)

    def _reconcile_deployment(self, name: str, info: _DeploymentInfo
                              ) -> bool:
        """One deployment's convergence step. Caller holds the lock.
        Returns True when membership (and thus the route table or the
        journal) changed."""
        changed = False
        # 0) condemn replicas on draining nodes — they need start-
        # before-stop replacement exactly like a stale version
        if self._draining_nodes:
            for h in list(info.replicas):
                if h in info.draining or h in info.condemned:
                    continue
                nid = self._lookup_replica_node(h)
                if nid and nid in self._draining_nodes:
                    info.condemned.add(h)
                    changed = True
        cur = [h for h, v in info.replicas.items()
               if v == info.version and h not in info.draining
               and h not in info.condemned]
        stale = [h for h in info.replicas
                 if h not in info.draining and h not in cur]
        target = max(0, info.target_replicas)
        surge = _max_surge()
        # 1) start-before-stop: bring the current version up first.
        # Initial deploys (no stale) scale straight to target; rolling
        # updates are bounded to `surge` extra replicas per wave.
        while len(cur) < target and len(cur) + len(stale) < target + surge:
            cur.append(self._start_replica(name, info))
            changed = True
        # 2) the health gate: drain stale replicas only one-for-one
        # against new replicas that PASSED health checks — a broken new
        # version never takes the old one down
        ready_cur = sum(1 for h in cur if h in info.ready)
        n_drain = min(len(stale), max(0, ready_cur + len(stale) - target))
        if n_drain:
            for h in self._least_loaded(name, stale)[:n_drain]:
                self._begin_drain(name, info, h, "rolling update")
                changed = True
        # 3) downscale: drain the least-loaded current-version replicas
        if len(cur) > target:
            for h in self._least_loaded(name, cur)[:len(cur) - target]:
                self._begin_drain(name, info, h, "downscale")
                changed = True
        return changed

    def _reconcile_once(self):
        changed = False
        drain_polls: List[Tuple[str, _DeploymentInfo, Any,
                                Dict[str, Any]]] = []
        with self._lock:
            for name, info in list(self._deployments.items()):
                try:
                    if self._reconcile_deployment(name, info):
                        changed = True
                        self._journal_deployment(name)
                    self._last_errors.pop(name, None)
                except Exception:
                    self._last_errors[name] = traceback.format_exc(limit=8)
                for h, st in info.draining.items():
                    drain_polls.append((name, info, h, st))
            self._journal_meta()
        # poll draining replicas outside the lock (an RPC per draining
        # replica; a wedged one must not block deploys/statuses)
        if self._poll_draining(drain_polls):
            changed = True
        if changed:
            self._publish_route_table()

    def _poll_draining(self, polls) -> bool:
        """Kill each draining replica once its in-flight count reaches
        zero or its grace deadline passes."""
        import ray_tpu
        if not polls:
            return False
        changed = False
        now = time.time()
        for name, info, h, st in polls:
            # give the replica-less route table one propagation window
            # (long-poll push is ~ms; 1 s covers a reconnecting client)
            # before shedding/killing
            if now - st.get("begun", now) < min(
                    1.0, max(0.0, st["deadline"] - st.get("begun", now))):
                continue
            if not st.get("notified"):
                st["notified"] = True
                try:
                    h.prepare_drain.remote()
                except Exception:
                    logger.debug("serve: prepare_drain to draining "
                                 "replica %s failed (dead? drain "
                                 "completes on the deadline)", name,
                                 exc_info=True)
            idle = False
            if now < st["deadline"]:
                try:
                    load = ray_tpu.get(h.get_load.remote(), timeout=2.0)
                    idle = load.get("queue_len", 0) <= 0
                except Exception:
                    idle = True  # dead/unreachable: nothing left to drain
            if not idle and now < st["deadline"]:
                continue
            self._stop_replica(h)
            with self._lock:
                self._forget_replica(info, h)
                live = self._deployments.get(name)
                if live is not None:
                    if live is not info:
                        self._forget_replica(live, h)
                    if live.config.get("_deleted") and not live.replicas:
                        del self._deployments[name]
                    self._journal_deployment(name)
            changed = True
        return changed

    @staticmethod
    def _llm_roles_map(info, replica_hexes) -> Optional[Dict[str, str]]:
        """Assign prefill/decode roles over a disaggregated LLM
        deployment's live replicas (``llm_roles`` in the config,
        e.g. ``{"prefill": 1, "decode": 2}``).  Assignment is by
        replica AGE (detached actor names carry a monotonically
        increasing #seq): the oldest ``n_prefill`` replicas prefill,
        the rest decode.  Age-stable ordering means a rolling update
        replaces roles one replica at a time instead of reshuffling
        the whole fleet on every wave."""
        roles_cfg = info.config.get("llm_roles")
        if not roles_cfg or not replica_hexes:
            return None
        ordered = sorted(replica_hexes,
                         key=lambda hx: info.replica_names.get(hx, hx))
        n_prefill = max(0, int(roles_cfg.get("prefill", 0)))
        out = {}
        for i, hx in enumerate(ordered):
            out[hx] = "prefill" if i < n_prefill else "decode"
        return out

    def _publish_route_table(self, force: bool = False):
        with self._lock:
            table = {}
            for name, info in self._deployments.items():
                if info.config.get("_deleted"):
                    continue
                replicas = [h._id_hex
                            for h in info.replicas
                            if h in info.ready
                            and h not in info.draining]
                table[name] = {
                    # only health-confirmed replicas carry traffic: a
                    # just-started (possibly broken) replica enters the
                    # table when its first probe passes, and a draining
                    # replica is already out — removal from the table
                    # is step 1 of the drain
                    "replicas": replicas,
                    "max_concurrent_queries":
                        info.config.get("max_concurrent_queries", 100),
                    "max_queued_requests":
                        info.config.get("max_queued_requests"),
                    "routing_policy": info.config.get("routing_policy"),
                    "route_prefix": info.config.get("route_prefix"),
                    "pass_http_path":
                        bool(info.config.get("pass_http_path")),
                    "pass_http_method":
                        bool(info.config.get("pass_http_method")),
                }
                roles = self._llm_roles_map(info, replicas)
                if roles is not None:
                    table[name]["replica_roles"] = roles
            if not force and table == self._last_published_table:
                return
            self._last_published_table = table
        self._long_poll.notify_changed("route_table", table)

    def _health_check(self):
        """Probe EVERY replica concurrently (one wedged probe no longer
        delays the others by its full timeout), and remove a replica
        only after ``RTPU_SERVE_HEALTH_FAILURES`` consecutive failures —
        except a definitively dead actor, which is removed at once."""
        import ray_tpu
        from ray_tpu import exceptions as rexc
        with self._lock:
            probes = [(name, info, h)
                      for name, info in self._deployments.items()
                      for h in list(info.replicas)
                      if h not in info.draining]
        if not probes:
            return
        refs = [h.check_health.remote() for _, _, h in probes]
        done, _pending = ray_tpu.wait(
            refs, num_returns=len(refs), timeout=_health_timeout_s())
        done_ids = {id(r) for r in done}
        threshold = _health_failure_threshold()
        removals = []
        newly_ready = False
        with self._lock:
            for (name, info, h), ref in zip(probes, refs):
                ok = dead = False
                if id(ref) in done_ids:
                    try:
                        ray_tpu.get(ref, timeout=5.0)
                        ok = True
                    except (rexc.ActorDiedError,
                            rexc.ActorUnavailableError):
                        dead = True
                    except Exception:
                        # user check_health raised / probe error:
                        # neither ok nor dead — counts as a miss below
                        logger.debug("serve: health probe on a %r "
                                     "replica errored", name,
                                     exc_info=True)
                if h not in info.replicas:
                    continue  # removed by a concurrent path meanwhile
                if ok:
                    info.health_fails.pop(h._id_hex, None)
                    if h not in info.ready:
                        info.ready.add(h)
                        newly_ready = True
                    continue
                fails = info.health_fails.get(h._id_hex, 0) + 1
                info.health_fails[h._id_hex] = fails
                if dead or fails >= threshold:
                    removals.append((name, info, h))
            for name, info, h in removals:
                logger.warning(
                    "serve: removing unhealthy replica %s of %r",
                    info.replica_names.get(h._id_hex, h._id_hex[:8]),
                    name)
                self._forget_replica(info, h)
                self._journal_deployment(name)
        if newly_ready:
            # a replica passing its FIRST probe enters the route table
            # (and may unlock the next rolling-update wave)
            self._publish_route_table()
        if removals:
            # routers must stop picking the dead replicas NOW — the next
            # reconcile replaces them, but the table with them removed
            # has to go out immediately
            self._publish_route_table()
            self._reconcile_once()

    def _metrics_tick(self):
        """Collect per-replica load (queue depth incl. the bounded
        waiting room + EWMA service time), publish it on the
        ``replica_load`` long-poll key for load-aware routing, and feed
        the same queue metrics to the autoscaler."""
        import ray_tpu
        now = time.time()
        with self._lock:
            items = [(name, info,
                      [h for h in info.replicas if h not in info.draining])
                     for name, info in self._deployments.items()
                     if not info.config.get("_deleted")]
        load_table: Dict[str, Dict[str, Any]] = {}
        for name, info, handles in items:
            per_replica = {}
            total_queue = 0.0
            for h in handles:
                try:
                    load = ray_tpu.get(h.get_load.remote(), timeout=5.0)
                    per_replica[h._id_hex] = load
                    total_queue += load.get("queue_len", 0)
                # dead/slow replica: the health check owns removal;
                # routers just won't get a fresh report for it
                except Exception:  # rtpulint: ignore[RTPU007]
                    pass
            if per_replica:
                load_table[name] = per_replica
            if info.autoscaler is not None:
                # queue_len (ongoing + queued) — a replica with a full
                # waiting room now registers as load even when its
                # execution slots cap num_ongoing. LLM replicas also
                # report engine telemetry (tokens/s, KV occupancy)
                # that the policy may scale on (docs/LLM_SERVING.md).
                decision = info.autoscaler.get_decision(
                    len(handles), total_queue, now,
                    signals=_aggregate_llm(
                        per_replica,
                        roles=self._llm_roles_map(
                            info, list(per_replica))))
                if decision != info.target_replicas:
                    with self._lock:
                        info.target_replicas = decision
                        self._journal_deployment(name)
        if load_table or self._last_load_table:
            self._last_load_table = load_table
            self._long_poll.notify_changed("replica_load", load_table)
