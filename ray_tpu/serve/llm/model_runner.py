"""Model adapters: the engine's prefill/decode contract.

An adapter owns the *storage* of the paged KV pool (the engine's
``PagedKVCache`` owns only the allocator) and exposes the compute
entry points:

    prefill(seqs) -> logits [B, V]   write the prompts' KV into their
                                     pages (skipping any cached-prefix
                                     tokens), return last-token logits
    decode(seqs)  -> logits [B, V]   append each sequence's newest
                                     sampled token, attend against the
                                     cached prefix, return next logits
    decode_window(seqs, windows)     speculative decode: append a
                                     window of tokens per sequence in
                                     ONE batched step and return the
                                     logits after every position
    rollback(seq_id, n)              retract the last n cached tokens
                                     (rejected speculative positions)
    copy_page(src, dst)              duplicate page contents (copy-on-
                                     write of a shared prefix page)
    export_kv / import_kv            serialize / rebind a prompt's KV
                                     pages for prefill→decode handoff

Two implementations:

* ``ToyAdapter`` — a dependency-free numpy language model whose next
  token is a deterministic function of the cached prefix READ BACK
  THROUGH THE BLOCK TABLES (a paging bug corrupts its output, which is
  exactly what the continuous-vs-static equivalence gate wants).
  Configurable per-step latency makes it the load-bearing workload for
  the game day and ``_BENCH_LLM`` without flax in the loop.

* ``FlaxModelAdapter`` — wraps ``models/gpt2.py`` / ``models/llama.py``
  incremental-decode paths: bucketed (batch, length) jit shapes, paged
  caches threaded through ``ops.attention.cached_attention``, padding
  rows parked on the null page. On TPU the single-token decode rides
  the ``paged_attention_decode`` Pallas kernel via the shared cached
  paths; on CPU the gather reference keeps numerics identical.
  ``decode_window`` reuses the same paged path — the multi-token
  incremental step is causal at the right offsets by construction
  (``q_positions = seq_lengths[:, None] + arange(S)``), so batched
  speculative verification is numerically the plain decode loop.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


def _pad_pow2(n: int, lo: int = 1) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


def _cached_tokens(seq) -> int:
    return int(getattr(seq, "cached_tokens", 0) or 0)


class ToyAdapter:
    """Deterministic numpy LM over the paged pool (tests, game day,
    bench). Each token's "KV" is its embedding; the next-token logits
    are ``mean(cached embeddings) @ E^T`` — prefix-dependent, exactly
    reproducible, and read through the block tables so paging bugs are
    visible as wrong tokens, not just wrong latency."""

    def __init__(self, vocab_size: int = 256, dim: int = 32,
                 seed: int = 0, step_delay_s: float = 0.0,
                 per_seq_delay_s: float = 0.0,
                 per_prefill_token_delay_s: float = 0.0):
        rng = np.random.RandomState(seed)
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.embed = rng.randn(self.vocab_size, self.dim).astype(
            np.float32)
        self.step_delay_s = float(step_delay_s)
        self.per_seq_delay_s = float(per_seq_delay_s)
        self.per_prefill_token_delay_s = float(per_prefill_token_delay_s)
        self._lock = threading.Lock()

    def bind_cache(self, cache):
        self.cache = cache
        self.pages = np.zeros(
            (cache.num_blocks, cache.block_size, self.dim), np.float32)
        # seq id -> {"table": np.ndarray pages, "len": cached tokens}
        self._state: Dict[str, Dict[str, Any]] = {}

    def copy_page(self, src: int, dst: int):
        self.pages[dst] = self.pages[src]

    def _write(self, st, tokens: List[int]):
        bs = self.cache.block_size
        table = st["table"]
        for i, tok in enumerate(tokens):
            pos = st["len"] + i
            self.pages[table[pos // bs], pos % bs] = self.embed[tok]
        st["len"] += len(tokens)

    def _logits(self, st) -> np.ndarray:
        bs = self.cache.block_size
        table = st["table"]
        n = st["len"]
        nb = -(-n // bs)
        flat = self.pages[table[:nb]].reshape(nb * bs, self.dim)[:n]
        h = flat.mean(axis=0)
        return (h @ self.embed.T).astype(np.float32)

    def _cow_partial_page(self, seq_id: str, st, cached: int):
        """A cached prefix ending mid-page means our first write lands
        in a shared page: take a private copy first (copy-on-extend)."""
        bs = self.cache.block_size
        if cached % bs == 0:
            return
        old, new = self.cache.copy_on_write(seq_id, cached // bs)
        if new != old:
            self.copy_page(old, new)
            st["table"] = np.asarray(
                self.cache.block_table(seq_id), np.int64)

    def prefill(self, seqs) -> np.ndarray:
        n_tok = sum(len(s.prompt) - _cached_tokens(s) for s in seqs)
        if self.step_delay_s or self.per_prefill_token_delay_s:
            time.sleep(self.step_delay_s
                       + self.per_prefill_token_delay_s * n_tok)
        out = np.zeros((len(seqs), self.vocab_size), np.float32)
        with self._lock:
            for i, s in enumerate(seqs):
                cached = _cached_tokens(s)
                st = {"table": np.asarray(
                    self.cache.block_table(s.seq_id), np.int64),
                    "len": cached}
                self._state[s.seq_id] = st
                self._cow_partial_page(s.seq_id, st, cached)
                self._write(st, s.prompt[cached:])
                out[i] = self._logits(st)
        return out

    def decode(self, seqs) -> np.ndarray:
        if self.step_delay_s or self.per_seq_delay_s:
            time.sleep(self.step_delay_s
                       + self.per_seq_delay_s * len(seqs))
        out = np.zeros((len(seqs), self.vocab_size), np.float32)
        with self._lock:
            for i, s in enumerate(seqs):
                st = self._state[s.seq_id]
                self._write(st, [s.tokens[-1]])
                out[i] = self._logits(st)
        return out

    def decode_window(self, seqs, windows) -> List[np.ndarray]:
        """Append each sequence's token window, returning logits after
        EVERY window position ([w_i, V] per sequence). The toy model is
        sequential anyway; the contract (and the flax implementation)
        is one batched step."""
        if self.step_delay_s or self.per_seq_delay_s:
            time.sleep(self.step_delay_s
                       + self.per_seq_delay_s * len(seqs))
        out = []
        with self._lock:
            for s, win in zip(seqs, windows):
                st = self._state[s.seq_id]
                rows = np.zeros((len(win), self.vocab_size), np.float32)
                for j, tok in enumerate(win):
                    self._write(st, [int(tok)])
                    rows[j] = self._logits(st)
                out.append(rows)
        return out

    def rollback(self, seq_id: str, n: int):
        with self._lock:
            st = self._state.get(seq_id)
            if st is not None and n > 0:
                st["len"] = max(0, st["len"] - int(n))

    def export_kv(self, seq_id: str, n_prompt: int) -> Dict[str, Any]:
        """Snapshot the prompt's KV pages for prefill→decode handoff."""
        bs = self.cache.block_size
        nb = -(-int(n_prompt) // bs)
        with self._lock:
            st = self._state[seq_id]
            table = np.asarray(st["table"][:nb], np.int64)
            return {"kind": "toy", "n": int(n_prompt),
                    "pages": self.pages[table].copy()}

    def import_kv(self, seq_id: str, n_prompt: int,
                  blob: Dict[str, Any]):
        """Rebind shipped prompt KV into this replica's (freshly
        allocated, private) pages."""
        if blob.get("kind") != "toy":
            raise ValueError("KV blob is not from a toy adapter")
        bs = self.cache.block_size
        nb = -(-int(n_prompt) // bs)
        with self._lock:
            table = np.asarray(
                self.cache.block_table(seq_id), np.int64)
            self.pages[table[:nb]] = blob["pages"]
            self._state[seq_id] = {"table": table, "len": int(n_prompt)}

    def release(self, seq_id: str):
        with self._lock:
            self._state.pop(seq_id, None)


class FlaxModelAdapter:
    """GPT-2 / Llama incremental decode over the paged pool.

    jit shapes are bucketed (batch to a power of two, prompt length to
    a power of two >= 8); padding rows carry zero lengths and
    null-page block tables, so they scatter into scratch and attend to
    nothing. Pages live as stacked per-layer jax arrays
    ([L, P, bs, Hkv, D]) and are donated through every step — the pool
    is updated in place, never copied.
    """

    def __init__(self, kind: str = "gpt2", config=None,
                 params=None, seed: int = 0):
        import jax
        import jax.numpy as jnp
        self._jnp = jnp
        self.kind = kind
        if kind == "gpt2":
            from ray_tpu.models import gpt2
            self.cfg = config or gpt2.GPT2Config.tiny()
            self.model = gpt2.GPT2(self.cfg)
            self.n_kv_heads = self.cfg.n_head
            self.head_dim = self.cfg.n_embd // self.cfg.n_head
            self.vocab_size = self.cfg.vocab_size
        elif kind == "llama":
            from ray_tpu.models import llama
            self.cfg = config or llama.LlamaConfig.tiny()
            self.model = llama.LlamaModel(self.cfg)
            self.n_kv_heads = self.cfg.n_kv_heads
            self.head_dim = self.cfg.head_dim
            self.vocab_size = self.cfg.vocab_size
        else:
            raise ValueError(f"unknown model kind {kind!r}")
        if params is None:
            dummy = jnp.zeros((1, 8), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed), dummy)
        self.params = params
        self._fns: Dict[Any, Any] = {}     # (B, S, full?) -> jitted step
        self._lock = threading.Lock()

    @property
    def n_layers(self) -> int:
        return getattr(self.cfg, "n_layer",
                       getattr(self.cfg, "n_layers", 0))

    def bind_cache(self, cache):
        jnp = self._jnp
        self.cache = cache
        dtype = self.cfg.dtype
        shape = (self.n_layers, cache.num_blocks, cache.block_size,
                 self.n_kv_heads, self.head_dim)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        # NB: every block table is padded to the worst-case blocks per
        # sequence so decode jits once per batch bucket
        self.nb_max = cache.blocks_for(
            getattr(self.cfg, "n_positions",
                    getattr(self.cfg, "max_seq_len", 2048)))
        self._state: Dict[str, Dict[str, Any]] = {}

    def copy_page(self, src: int, dst: int):
        with self._lock:
            self.k_pages = self.k_pages.at[:, dst].set(
                self.k_pages[:, src])
            self.v_pages = self.v_pages.at[:, dst].set(
                self.v_pages[:, src])

    def _step_fn(self, B: int, S: int, full: bool = False):
        key = (B, S, full)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        import jax
        jnp = self._jnp
        L = self.n_layers

        def step(params, tokens, k_pages, v_pages, block_tables,
                 seq_lengths, valid):
            caches = [{"k_pages": k_pages[l], "v_pages": v_pages[l],
                       "block_tables": block_tables}
                      for l in range(L)]
            logits, new = self.model.apply(
                params, tokens, kv_cache=caches,
                seq_lengths=seq_lengths, valid=valid)
            k_new = jnp.stack([c["k_pages"] for c in new])
            v_new = jnp.stack([c["v_pages"] for c in new])
            if full:
                # speculative verify reads logits at EVERY position
                return logits, k_new, v_new
            # last REAL token's logits per row
            idx = jnp.maximum(
                jnp.sum(valid.astype(jnp.int32), axis=1) - 1, 0)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]
            return last, k_new, v_new

        # donate the pools on TPU (in-place page update, zero copy);
        # CPU ignores donation and would warn on every compile
        donate = (2, 3) if jax.devices()[0].platform == "tpu" else ()
        fn = jax.jit(step, donate_argnums=donate)
        self._fns[key] = fn
        return fn

    def _run(self, rows: List[Dict[str, Any]],
             full: bool = False) -> np.ndarray:
        """rows: [{tokens: [ints], len: cache length, table: [pages]}]
        -> last-token logits [B, V] (or full [B, S, V] when ``full``)
        for the real rows."""
        jnp = self._jnp
        B = _pad_pow2(len(rows))
        S = _pad_pow2(max(len(r["tokens"]) for r in rows), 8)
        tokens = np.zeros((B, S), np.int32)
        lengths = np.zeros((B,), np.int32)
        valid = np.zeros((B, S), bool)
        tables = np.zeros((B, self.nb_max), np.int32)
        for i, r in enumerate(rows):
            n = len(r["tokens"])
            tokens[i, :n] = r["tokens"]
            lengths[i] = r["len"]
            valid[i, :n] = True
            t = r["table"][:self.nb_max]
            tables[i, :len(t)] = t
        fn = self._step_fn(B, S, full)
        with self._lock:
            logits, self.k_pages, self.v_pages = fn(
                self.params, jnp.asarray(tokens), self.k_pages,
                self.v_pages, jnp.asarray(tables),
                jnp.asarray(lengths), jnp.asarray(valid))
        return np.asarray(logits[:len(rows)], np.float32)

    def prefill(self, seqs) -> np.ndarray:
        rows = []
        for s in seqs:
            cached = _cached_tokens(s)
            if cached % self.cache.block_size:
                # copy-on-extend: the suffix write lands in the last
                # shared prefix page — privatize it first
                old, new = self.cache.copy_on_write(
                    s.seq_id, cached // self.cache.block_size)
                if new != old:
                    self.copy_page(old, new)
            table = self.cache.block_table(s.seq_id)
            self._state[s.seq_id] = {"table": table,
                                     "len": len(s.prompt)}
            rows.append({"tokens": s.prompt[cached:], "len": cached,
                         "table": table})
        return self._run(rows)

    def decode(self, seqs) -> np.ndarray:
        rows = []
        for s in seqs:
            st = self._state[s.seq_id]
            rows.append({"tokens": [s.tokens[-1]], "len": st["len"],
                         "table": st["table"]})
            st["len"] += 1
        return self._run(rows)

    def decode_window(self, seqs, windows) -> List[np.ndarray]:
        """One batched multi-token incremental step; causal masking at
        the right offsets comes from ``cached_attention``'s
        ``q_positions``, so position j's logits condition on exactly
        window[:j+1] — the speculative verify contract."""
        rows = []
        for s, win in zip(seqs, windows):
            st = self._state[s.seq_id]
            rows.append({"tokens": list(win), "len": st["len"],
                         "table": st["table"]})
            st["len"] += len(win)
        full = self._run(rows, full=True)      # [B, S, V]
        return [full[i, :len(win)] for i, win in enumerate(windows)]

    def rollback(self, seq_id: str, n: int):
        st = self._state.get(seq_id)
        if st is not None and n > 0:
            st["len"] = max(0, st["len"] - int(n))

    def export_kv(self, seq_id: str, n_prompt: int) -> Dict[str, Any]:
        jnp = self._jnp
        bs = self.cache.block_size
        nb = -(-int(n_prompt) // bs)
        st = self._state[seq_id]
        idx = jnp.asarray(np.asarray(st["table"][:nb], np.int32))
        with self._lock:
            k = np.asarray(self.k_pages[:, idx])
            v = np.asarray(self.v_pages[:, idx])
        return {"kind": f"flax:{self.kind}", "n": int(n_prompt),
                "k": k, "v": v}

    def import_kv(self, seq_id: str, n_prompt: int,
                  blob: Dict[str, Any]):
        jnp = self._jnp
        if blob.get("kind") != f"flax:{self.kind}":
            raise ValueError(
                f"KV blob kind {blob.get('kind')!r} does not match "
                f"adapter flax:{self.kind}")
        bs = self.cache.block_size
        nb = -(-int(n_prompt) // bs)
        table = self.cache.block_table(seq_id)
        idx = jnp.asarray(np.asarray(table[:nb], np.int32))
        with self._lock:
            self.k_pages = self.k_pages.at[:, idx].set(
                jnp.asarray(blob["k"], self.k_pages.dtype))
            self.v_pages = self.v_pages.at[:, idx].set(
                jnp.asarray(blob["v"], self.v_pages.dtype))
        self._state[seq_id] = {"table": table, "len": int(n_prompt)}

    def release(self, seq_id: str):
        self._state.pop(seq_id, None)


def make_adapter(model: str = "toy",
                 model_config: Optional[Dict[str, Any]] = None):
    """Deployment-facing factory: ``model`` is ``toy`` |
    ``gpt2`` | ``llama`` (tiny test configs unless ``model_config``
    overrides)."""
    model_config = dict(model_config or {})
    if model == "toy":
        return ToyAdapter(**model_config)
    if model in ("gpt2", "llama"):
        return FlaxModelAdapter(kind=model, **model_config)
    raise ValueError(f"unknown model {model!r} (toy | gpt2 | llama)")
