"""Model adapters: the engine's prefill/decode contract.

An adapter owns the *storage* of the paged KV pool (the engine's
``PagedKVCache`` owns only the allocator) and exposes exactly two
compute entry points:

    prefill(seqs) -> logits [B, V]   write the prompts' KV into their
                                     pages, return last-token logits
    decode(seqs)  -> logits [B, V]   append each sequence's newest
                                     sampled token, attend against the
                                     cached prefix, return next logits

Two implementations:

* ``ToyAdapter`` — a dependency-free numpy language model whose next
  token is a deterministic function of the cached prefix READ BACK
  THROUGH THE BLOCK TABLES (a paging bug corrupts its output, which is
  exactly what the continuous-vs-static equivalence gate wants).
  Configurable per-step latency makes it the load-bearing workload for
  the game day and ``_BENCH_LLM`` without flax in the loop.

* ``FlaxModelAdapter`` — wraps ``models/gpt2.py`` / ``models/llama.py``
  incremental-decode paths: bucketed (batch, length) jit shapes, paged
  caches threaded through ``ops.attention.cached_attention``, padding
  rows parked on the null page. On TPU the single-token decode rides
  the ``paged_attention_decode`` Pallas kernel via the shared cached
  paths; on CPU the gather reference keeps numerics identical.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


def _pad_pow2(n: int, lo: int = 1) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


class ToyAdapter:
    """Deterministic numpy LM over the paged pool (tests, game day,
    bench). Each token's "KV" is its embedding; the next-token logits
    are ``mean(cached embeddings) @ E^T`` — prefix-dependent, exactly
    reproducible, and read through the block tables so paging bugs are
    visible as wrong tokens, not just wrong latency."""

    def __init__(self, vocab_size: int = 256, dim: int = 32,
                 seed: int = 0, step_delay_s: float = 0.0,
                 per_seq_delay_s: float = 0.0,
                 per_prefill_token_delay_s: float = 0.0):
        rng = np.random.RandomState(seed)
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.embed = rng.randn(self.vocab_size, self.dim).astype(
            np.float32)
        self.step_delay_s = float(step_delay_s)
        self.per_seq_delay_s = float(per_seq_delay_s)
        self.per_prefill_token_delay_s = float(per_prefill_token_delay_s)
        self._lock = threading.Lock()

    def bind_cache(self, cache):
        self.cache = cache
        self.pages = np.zeros(
            (cache.num_blocks, cache.block_size, self.dim), np.float32)
        # seq id -> {"table": np.ndarray pages, "len": cached tokens}
        self._state: Dict[str, Dict[str, Any]] = {}

    def _write(self, st, tokens: List[int]):
        bs = self.cache.block_size
        table = st["table"]
        for i, tok in enumerate(tokens):
            pos = st["len"] + i
            self.pages[table[pos // bs], pos % bs] = self.embed[tok]
        st["len"] += len(tokens)

    def _logits(self, st) -> np.ndarray:
        bs = self.cache.block_size
        table = st["table"]
        n = st["len"]
        nb = -(-n // bs)
        flat = self.pages[table[:nb]].reshape(nb * bs, self.dim)[:n]
        h = flat.mean(axis=0)
        return (h @ self.embed.T).astype(np.float32)

    def prefill(self, seqs) -> np.ndarray:
        n_tok = sum(len(s.prompt) for s in seqs)
        if self.step_delay_s or self.per_prefill_token_delay_s:
            time.sleep(self.step_delay_s
                       + self.per_prefill_token_delay_s * n_tok)
        out = np.zeros((len(seqs), self.vocab_size), np.float32)
        with self._lock:
            for i, s in enumerate(seqs):
                st = {"table": np.asarray(
                    self.cache.block_table(s.seq_id), np.int64),
                    "len": 0}
                self._state[s.seq_id] = st
                self._write(st, s.prompt)
                out[i] = self._logits(st)
        return out

    def decode(self, seqs) -> np.ndarray:
        if self.step_delay_s or self.per_seq_delay_s:
            time.sleep(self.step_delay_s
                       + self.per_seq_delay_s * len(seqs))
        out = np.zeros((len(seqs), self.vocab_size), np.float32)
        with self._lock:
            for i, s in enumerate(seqs):
                st = self._state[s.seq_id]
                self._write(st, [s.tokens[-1]])
                out[i] = self._logits(st)
        return out

    def release(self, seq_id: str):
        with self._lock:
            self._state.pop(seq_id, None)


class FlaxModelAdapter:
    """GPT-2 / Llama incremental decode over the paged pool.

    jit shapes are bucketed (batch to a power of two, prompt length to
    a power of two >= 8); padding rows carry zero lengths and
    null-page block tables, so they scatter into scratch and attend to
    nothing. Pages live as stacked per-layer jax arrays
    ([L, P, bs, Hkv, D]) and are donated through every step — the pool
    is updated in place, never copied.
    """

    def __init__(self, kind: str = "gpt2", config=None,
                 params=None, seed: int = 0):
        import jax
        import jax.numpy as jnp
        self._jnp = jnp
        self.kind = kind
        if kind == "gpt2":
            from ray_tpu.models import gpt2
            self.cfg = config or gpt2.GPT2Config.tiny()
            self.model = gpt2.GPT2(self.cfg)
            self.n_kv_heads = self.cfg.n_head
            self.head_dim = self.cfg.n_embd // self.cfg.n_head
            self.vocab_size = self.cfg.vocab_size
        elif kind == "llama":
            from ray_tpu.models import llama
            self.cfg = config or llama.LlamaConfig.tiny()
            self.model = llama.LlamaModel(self.cfg)
            self.n_kv_heads = self.cfg.n_kv_heads
            self.head_dim = self.cfg.head_dim
            self.vocab_size = self.cfg.vocab_size
        else:
            raise ValueError(f"unknown model kind {kind!r}")
        if params is None:
            dummy = jnp.zeros((1, 8), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed), dummy)
        self.params = params
        self._fns: Dict[Any, Any] = {}     # (B, S, NB) -> jitted step
        self._lock = threading.Lock()

    @property
    def n_layers(self) -> int:
        return getattr(self.cfg, "n_layer",
                       getattr(self.cfg, "n_layers", 0))

    def bind_cache(self, cache):
        jnp = self._jnp
        self.cache = cache
        dtype = self.cfg.dtype
        shape = (self.n_layers, cache.num_blocks, cache.block_size,
                 self.n_kv_heads, self.head_dim)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)
        # NB: every block table is padded to the worst-case blocks per
        # sequence so decode jits once per batch bucket
        self.nb_max = cache.blocks_for(
            getattr(self.cfg, "n_positions",
                    getattr(self.cfg, "max_seq_len", 2048)))
        self._state: Dict[str, Dict[str, Any]] = {}

    def _step_fn(self, B: int, S: int):
        key = (B, S)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        import jax
        jnp = self._jnp
        L = self.n_layers

        def step(params, tokens, k_pages, v_pages, block_tables,
                 seq_lengths, valid):
            caches = [{"k_pages": k_pages[l], "v_pages": v_pages[l],
                       "block_tables": block_tables}
                      for l in range(L)]
            logits, new = self.model.apply(
                params, tokens, kv_cache=caches,
                seq_lengths=seq_lengths, valid=valid)
            k_new = jnp.stack([c["k_pages"] for c in new])
            v_new = jnp.stack([c["v_pages"] for c in new])
            # last REAL token's logits per row
            idx = jnp.maximum(
                jnp.sum(valid.astype(jnp.int32), axis=1) - 1, 0)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]
            return last, k_new, v_new

        # donate the pools on TPU (in-place page update, zero copy);
        # CPU ignores donation and would warn on every compile
        donate = (2, 3) if jax.devices()[0].platform == "tpu" else ()
        fn = jax.jit(step, donate_argnums=donate)
        self._fns[key] = fn
        return fn

    def _run(self, rows: List[Dict[str, Any]]) -> np.ndarray:
        """rows: [{tokens: [ints], len: cache length, table: [pages]}]
        -> last-token logits for the real rows."""
        jnp = self._jnp
        B = _pad_pow2(len(rows))
        S = _pad_pow2(max(len(r["tokens"]) for r in rows), 8)
        tokens = np.zeros((B, S), np.int32)
        lengths = np.zeros((B,), np.int32)
        valid = np.zeros((B, S), bool)
        tables = np.zeros((B, self.nb_max), np.int32)
        for i, r in enumerate(rows):
            n = len(r["tokens"])
            tokens[i, :n] = r["tokens"]
            lengths[i] = r["len"]
            valid[i, :n] = True
            t = r["table"][:self.nb_max]
            tables[i, :len(t)] = t
        fn = self._step_fn(B, S)
        with self._lock:
            logits, self.k_pages, self.v_pages = fn(
                self.params, jnp.asarray(tokens), self.k_pages,
                self.v_pages, jnp.asarray(tables),
                jnp.asarray(lengths), jnp.asarray(valid))
        return np.asarray(logits[:len(rows)], np.float32)

    def prefill(self, seqs) -> np.ndarray:
        rows = []
        for s in seqs:
            table = self.cache.block_table(s.seq_id)
            self._state[s.seq_id] = {"table": table,
                                     "len": len(s.prompt)}
            rows.append({"tokens": s.prompt, "len": 0, "table": table})
        return self._run(rows)

    def decode(self, seqs) -> np.ndarray:
        rows = []
        for s in seqs:
            st = self._state[s.seq_id]
            rows.append({"tokens": [s.tokens[-1]], "len": st["len"],
                         "table": st["table"]})
            st["len"] += 1
        return self._run(rows)

    def release(self, seq_id: str):
        self._state.pop(seq_id, None)


def make_adapter(model: str = "toy",
                 model_config: Optional[Dict[str, Any]] = None):
    """Deployment-facing factory: ``model`` is ``toy`` |
    ``gpt2`` | ``llama`` (tiny test configs unless ``model_config``
    overrides)."""
    model_config = dict(model_config or {})
    if model == "toy":
        return ToyAdapter(**model_config)
    if model in ("gpt2", "llama"):
        return FlaxModelAdapter(kind=model, **model_config)
    raise ValueError(f"unknown model {model!r} (toy | gpt2 | llama)")
