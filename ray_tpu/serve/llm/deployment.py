"""LLMServer — the serve deployment callable hosting one engine.

The first genuinely *stateful* serve workload: a replica holds an
``LLMEngine`` (continuous batching + paged KV cache) and exposes it
through the standard replica request path, so routing, backpressure,
shedding, ledgers, tracing, HA and drain all apply unchanged:

  ``__call__(payload)``            unary generate (existing proxy path)
  ``__llm_open__(payload)``        start a stream -> {"stream_id"}
  ``__llm_next__(sid, cursor, w)`` cursor poll -> token delta
  ``__llm_cancel__(sid)``          abandon a stream
  ``__llm_metrics__()``            engine metrics + token ledger

Serve integration hooks (consumed by ``_private/replica.py``):

  ``__serve_load__``         merged into ``get_load`` — in-flight
                             sequences count as queue depth (the
                             controller's drain poll waits for them:
                             KV-aware graceful drain) and the ``llm``
                             metrics ride the controller's telemetry
                             into the autoscaler + Prometheus
  ``__serve_prepare_drain__`` engine stops admitting, finishes decodes
  ``__serve_drain_exempt__``  stream polls stay answerable while
                             draining — an in-flight stream must be
                             able to read its remaining tokens
  ``__serve_prepare_shutdown__`` flush the per-request token ledger to
                             the GCS KV so a replica retired by a
                             rolling update keeps its half of the
                             game-day per-token reconciliation

Payload schema (dict): ``prompt`` (str, byte-tokenized) or ``tokens``
(list[int]); optional ``max_new_tokens``, ``temperature``, ``seed``,
``stop_token``, ``stream`` (proxy SSE opt-in), ``echo_text``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from ray_tpu.serve.llm.engine import (EngineConfig, LLMEngine,
                                      SamplingParams)
from ray_tpu.serve.llm.model_runner import make_adapter


class ByteTokenizer:
    """Dependency-free fallback: UTF-8 bytes, mod vocab. Real models
    bring their own tokenizer; the toy/test path just needs a stable
    string <-> tokens round trip."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str) -> List[int]:
        return [b % self.vocab_size for b in text.encode("utf-8")]

    def decode(self, tokens: List[int]) -> str:
        return bytes(t % 256 for t in tokens).decode("utf-8", "replace")


class LLMServer:
    """Deployment callable: one engine per replica."""

    # replica keeps answering these while draining — in-flight streams
    # must drain their remaining tokens before the controller's kill
    __serve_drain_exempt__ = ("__llm_next__", "__llm_cancel__",
                              "__llm_metrics__")
    # the replica normally strips the reserved request-id kwarg; the
    # engine needs it for the per-request token ledger + trace spans
    __serve_wants_request_id__ = True

    def __init__(self, model: str = "toy",
                 model_config: Optional[Dict[str, Any]] = None,
                 engine_config: Optional[Dict[str, Any]] = None):
        self.adapter = make_adapter(model, model_config)
        cfg = EngineConfig(**(engine_config or {}))
        self.engine = LLMEngine(self.adapter, cfg)
        self.tokenizer = ByteTokenizer(self.adapter.vocab_size)
        self.model = model

    # ------------------------------------------------------------ intake

    def _tokens_of(self, payload: Union[Dict[str, Any], str, list]
                   ) -> List[int]:
        if isinstance(payload, str):
            return self.tokenizer.encode(payload)
        if isinstance(payload, list):
            return [int(t) for t in payload]
        if isinstance(payload, dict):
            if payload.get("tokens") is not None:
                return [int(t) for t in payload["tokens"]]
            if payload.get("prompt") is not None:
                return self.tokenizer.encode(str(payload["prompt"]))
        raise ValueError(
            "LLM payload needs 'prompt' (str) or 'tokens' (list[int])")

    def _open(self, payload, request_id: Optional[str]) -> str:
        sampling = (SamplingParams.from_payload(payload)
                    if isinstance(payload, dict) else SamplingParams())
        # parent the engine's phase spans under THIS request's replica
        # execute span (installed by replica._execute for sampled
        # requests) so TTFT decomposes on the trace waterfall
        trace_ctx = None
        try:
            from ray_tpu._private import worker as worker_mod
            w = worker_mod._global_worker
            if w is not None:
                trace_ctx = getattr(w.task_context, "trace", None)
        except Exception:
            pass
        return self.engine.add_request(
            self._tokens_of(payload), sampling, request_id=request_id,
            trace_ctx=dict(trace_ctx) if trace_ctx else None)

    # --------------------------------------------------------- serve API

    def __call__(self, payload=None, __rtpu_request_id__=None):
        """Unary generation (the stateless-looking path: proxy POST
        without ``stream``, plain ``handle.remote``)."""
        rid = __rtpu_request_id__
        sid = self._open(payload or {}, rid)
        cursor = 0
        tokens: List[int] = []
        ttft = None
        while True:
            chunk = self.engine.poll(sid, cursor, max_wait_s=30.0)
            tokens.extend(chunk["tokens"])
            cursor = chunk["cursor"]
            if chunk.get("ttft_s") is not None:
                ttft = chunk["ttft_s"]
            if chunk["done"]:
                if chunk.get("error"):
                    raise RuntimeError(
                        f"generation failed: {chunk['error']}")
                out = {"tokens": tokens, "n_tokens": len(tokens),
                       "finish_reason": chunk.get("finish_reason"),
                       "text": self.tokenizer.decode(tokens)}
                if ttft is not None:
                    out["ttft_s"] = ttft
                return out

    def __llm_open__(self, payload=None, __rtpu_request_id__=None):
        sid = self._open(payload or {}, __rtpu_request_id__)
        return {"stream_id": sid}

    def __llm_next__(self, stream_id: str, cursor: int = 0,
                     max_wait_s: float = 10.0):
        chunk = self.engine.poll(stream_id, int(cursor),
                                 max_wait_s=float(max_wait_s))
        if chunk["tokens"]:
            chunk["text"] = self.tokenizer.decode(chunk["tokens"])
        return chunk

    def __llm_cancel__(self, stream_id: str):
        return {"cancelled": self.engine.cancel(stream_id)}

    def __llm_metrics__(self):
        m = self.engine.metrics()
        m["token_ledger"] = self.engine.token_ledger()
        return m

    # ------------------------------------------------- serve integration

    def __serve_load__(self) -> Dict[str, Any]:
        m = self.engine.metrics()
        return {
            # in-flight sequences ARE queue depth: the router's p2c
            # scoring sees decode load, the autoscaler sees pressure,
            # and the controller's drain poll waits for zero
            "queue_len_extra": m["running"] + m["waiting"],
            "llm": m,
        }

    def __serve_prepare_drain__(self):
        self.engine.prepare_drain()

    def __serve_prepare_shutdown__(self, replica_name: str = ""):
        """Best-effort token-ledger flush (rolling update / downscale):
        reconciliation joins client token counts against it even after
        this replica is gone."""
        try:
            from ray_tpu.gameday import store
            ledger = self.engine.token_ledger()
            if ledger:
                store.flush_llm_ledger(replica_name, ledger)
        except Exception:
            pass
        try:
            self.engine.stop()
        except Exception:
            pass

    def check_health(self):
        if not self.engine._thread.is_alive():
            raise RuntimeError("LLM engine thread died")
        return "ok"
