"""LLMServer — the serve deployment callable hosting one engine.

The first genuinely *stateful* serve workload: a replica holds an
``LLMEngine`` (continuous batching + paged KV cache) and exposes it
through the standard replica request path, so routing, backpressure,
shedding, ledgers, tracing, HA and drain all apply unchanged:

  ``__call__(payload)``            unary generate (existing proxy path)
  ``__llm_open__(payload)``        start a stream -> {"stream_id"}
  ``__llm_next__(sid, cursor, w)`` cursor poll -> token delta
  ``__llm_cancel__(sid)``          abandon a stream
  ``__llm_metrics__()``            engine metrics + token ledger
  ``__llm_prefill__(payload)``     disagg hop 1: prompt + first token,
                                   returns a KV handoff descriptor
  ``__llm_adopt__(handoff)``       disagg hop 2: rebind the shipped KV
                                   (or re-prefill on a torn frame) ->
                                   {"stream_id", "adopted"}

Serve integration hooks (consumed by ``_private/replica.py``):

  ``__serve_load__``         merged into ``get_load`` — in-flight
                             sequences count as queue depth (the
                             controller's drain poll waits for them:
                             KV-aware graceful drain) and the ``llm``
                             metrics ride the controller's telemetry
                             into the autoscaler + Prometheus
  ``__serve_prepare_drain__`` engine stops admitting, finishes decodes
  ``__serve_drain_exempt__``  stream polls stay answerable while
                             draining — an in-flight stream must be
                             able to read its remaining tokens
  ``__serve_prepare_shutdown__`` flush the per-request token ledger to
                             the GCS KV so a replica retired by a
                             rolling update keeps its half of the
                             game-day per-token reconciliation

Payload schema (dict): ``prompt`` (str, byte-tokenized) or ``tokens``
(list[int]); optional ``max_new_tokens``, ``temperature``, ``seed``,
``stop_token``, ``stream`` (proxy SSE opt-in), ``echo_text``.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional, Union

from ray_tpu.serve.llm.disagg import KVShipError, KVShipper
from ray_tpu.serve.llm.engine import (EngineConfig, LLMEngine,
                                      SamplingParams)
from ray_tpu.serve.llm.model_runner import make_adapter

logger = logging.getLogger(__name__)


class ByteTokenizer:
    """Dependency-free fallback: UTF-8 bytes, mod vocab. Real models
    bring their own tokenizer; the toy/test path just needs a stable
    string <-> tokens round trip."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str) -> List[int]:
        return [b % self.vocab_size for b in text.encode("utf-8")]

    def decode(self, tokens: List[int]) -> str:
        return bytes(t % 256 for t in tokens).decode("utf-8", "replace")


class LLMServer:
    """Deployment callable: one engine per replica."""

    # replica keeps answering these while draining — in-flight streams
    # must drain their remaining tokens before the controller's kill
    __serve_drain_exempt__ = ("__llm_next__", "__llm_cancel__",
                              "__llm_metrics__")
    # the replica normally strips the reserved request-id kwarg; the
    # engine needs it for the per-request token ledger + trace spans
    __serve_wants_request_id__ = True

    def __init__(self, model: str = "toy",
                 model_config: Optional[Dict[str, Any]] = None,
                 engine_config: Optional[Dict[str, Any]] = None):
        self.adapter = make_adapter(model, model_config)
        cfg = EngineConfig(**(engine_config or {}))
        self.engine = LLMEngine(self.adapter, cfg)
        self.tokenizer = ByteTokenizer(self.adapter.vocab_size)
        self.model = model
        self._shipper: Optional[KVShipper] = None

    def _get_shipper(self) -> KVShipper:
        if self._shipper is None:
            self._shipper = KVShipper(f"{os.getpid()}-{id(self)}")
        return self._shipper

    @staticmethod
    def _trace_ctx() -> Optional[Dict[str, str]]:
        # parent the engine's phase spans under THIS request's replica
        # execute span (installed by replica._execute for sampled
        # requests) so TTFT decomposes on the trace waterfall
        try:
            from ray_tpu._private import worker as worker_mod
            w = worker_mod._global_worker
            if w is not None:
                ctx = getattr(w.task_context, "trace", None)
                return dict(ctx) if ctx else None
        except Exception:
            pass
        return None

    # ------------------------------------------------------------ intake

    def _tokens_of(self, payload: Union[Dict[str, Any], str, list]
                   ) -> List[int]:
        if isinstance(payload, str):
            return self.tokenizer.encode(payload)
        if isinstance(payload, list):
            return [int(t) for t in payload]
        if isinstance(payload, dict):
            if payload.get("tokens") is not None:
                return [int(t) for t in payload["tokens"]]
            if payload.get("prompt") is not None:
                return self.tokenizer.encode(str(payload["prompt"]))
        raise ValueError(
            "LLM payload needs 'prompt' (str) or 'tokens' (list[int])")

    def _open(self, payload, request_id: Optional[str]) -> str:
        sampling = (SamplingParams.from_payload(payload)
                    if isinstance(payload, dict) else SamplingParams())
        return self.engine.add_request(
            self._tokens_of(payload), sampling, request_id=request_id,
            trace_ctx=self._trace_ctx())

    # --------------------------------------------------------- serve API

    def __call__(self, payload=None, __rtpu_request_id__=None):
        """Unary generation (the stateless-looking path: proxy POST
        without ``stream``, plain ``handle.remote``)."""
        rid = __rtpu_request_id__
        sid = self._open(payload or {}, rid)
        cursor = 0
        tokens: List[int] = []
        ttft = None
        while True:
            chunk = self.engine.poll(sid, cursor, max_wait_s=30.0)
            tokens.extend(chunk["tokens"])
            cursor = chunk["cursor"]
            if chunk.get("ttft_s") is not None:
                ttft = chunk["ttft_s"]
            if chunk["done"]:
                if chunk.get("error"):
                    raise RuntimeError(
                        f"generation failed: {chunk['error']}")
                out = {"tokens": tokens, "n_tokens": len(tokens),
                       "finish_reason": chunk.get("finish_reason"),
                       "text": self.tokenizer.decode(tokens)}
                if ttft is not None:
                    out["ttft_s"] = ttft
                return out

    def __llm_open__(self, payload=None, __rtpu_request_id__=None):
        sid = self._open(payload or {}, __rtpu_request_id__)
        return {"stream_id": sid}

    def __llm_next__(self, stream_id: str, cursor: int = 0,
                     max_wait_s: float = 10.0):
        chunk = self.engine.poll(stream_id, int(cursor),
                                 max_wait_s=float(max_wait_s))
        if chunk["tokens"]:
            chunk["text"] = self.tokenizer.decode(chunk["tokens"])
        return chunk

    def __llm_cancel__(self, stream_id: str):
        return {"cancelled": self.engine.cancel(stream_id)}

    # -------------------------------------- disaggregation (disagg.py)

    def __llm_prefill__(self, payload=None, __rtpu_request_id__=None):
        """Disagg hop 1 (prefill replica): run prompt + ONE token,
        snapshot the prompt's KV pages, and return a handoff
        descriptor the router carries to a decode replica.  The
        descriptor always includes the prompt + sampling so the decode
        side can re-prefill if the KV frame is lost."""
        payload = payload or {}
        sampling = (SamplingParams.from_payload(payload)
                    if isinstance(payload, dict) else SamplingParams())
        tokens = self._tokens_of(payload)
        sid = self.engine.prefill_export(
            tokens, sampling, request_id=__rtpu_request_id__,
            trace_ctx=self._trace_ctx())
        cursor = 0
        while True:
            chunk = self.engine.poll(sid, cursor, max_wait_s=30.0)
            cursor = chunk["cursor"]
            if chunk["done"]:
                break
        if chunk.get("error"):
            raise RuntimeError(f"prefill failed: {chunk['error']}")
        export = self.engine.take_export(sid) or {}
        first = export.get("first_token")
        if first is None:
            raise RuntimeError("prefill produced no first token")
        handoff: Dict[str, Any] = {
            "prompt": tokens,
            "first_token": int(first),
            "n_prompt": len(tokens),
            "sampling": sampling.to_payload(),
            "t_ship_start": time.time(),
        }
        terminal = (sampling.max_new_tokens <= 1
                    or (sampling.stop_token is not None
                        and int(first) == sampling.stop_token))
        if not terminal and export.get("kv") is not None:
            handoff["kv"] = self._get_shipper().ship({"kv": export["kv"]})
        return handoff

    def __llm_adopt__(self, handoff=None, __rtpu_request_id__=None):
        """Disagg hop 2 (decode replica): fetch the KV frame, rebind
        its pages into this replica's pool, and continue decoding from
        the prefill replica's first token.  Any transport fault —
        chaos drop/reset, CRC mismatch, vanished ring slot, blob
        mismatch — falls back to a local re-prefill: greedy decode is
        deterministic, so the stream is output-identical."""
        handoff = handoff or {}
        rid = __rtpu_request_id__
        trace_ctx = self._trace_ctx()
        prompt = [int(t) for t in handoff.get("prompt") or []]
        sampling = SamplingParams.from_payload(
            dict(handoff.get("sampling") or {}))
        first = handoff.get("first_token")
        terminal = (sampling.max_new_tokens <= 1
                    or (sampling.stop_token is not None and first is not None
                        and int(first) == sampling.stop_token))
        if terminal and first is not None:
            sid = self.engine.adopt_request(
                prompt, int(first), None, sampling, request_id=rid,
                trace_ctx=trace_ctx)
            return {"stream_id": sid, "adopted": True}
        blob = None
        desc = handoff.get("kv")
        if desc is not None and first is not None:
            try:
                frame = self._get_shipper().receive(
                    desc, method="__llm_adopt__")
            except KVShipError:
                frame = None
            blob = (frame or {}).get("kv")
        if blob is not None:
            try:
                sid = self.engine.adopt_request(
                    prompt, int(first), blob, sampling, request_id=rid,
                    trace_ctx=trace_ctx,
                    lane=desc.get("lane", "inline"),
                    t_ship_start=handoff.get("t_ship_start"))
                return {"stream_id": sid, "adopted": True}
            except Exception:
                logger.warning(
                    "llm.kv_ship: adoption failed, re-prefilling",
                    exc_info=True)
        # fallback: deterministic re-prefill on this (decode) replica;
        # sheds retriably if this replica is saturated
        sid = self.engine.add_request(prompt, sampling, request_id=rid,
                                      trace_ctx=trace_ctx)
        return {"stream_id": sid, "adopted": False}

    def __llm_metrics__(self):
        m = self.engine.metrics()
        m["token_ledger"] = self.engine.token_ledger()
        return m

    # ------------------------------------------------- serve integration

    def __serve_load__(self) -> Dict[str, Any]:
        m = self.engine.metrics()
        return {
            # in-flight sequences ARE queue depth: the router's p2c
            # scoring sees decode load, the autoscaler sees pressure,
            # and the controller's drain poll waits for zero
            "queue_len_extra": m["running"] + m["waiting"],
            "llm": m,
        }

    def __serve_prepare_drain__(self):
        self.engine.prepare_drain()

    def __serve_prepare_shutdown__(self, replica_name: str = ""):
        """Best-effort token-ledger flush (rolling update / downscale):
        reconciliation joins client token counts against it even after
        this replica is gone."""
        try:
            from ray_tpu.gameday import store
            ledger = self.engine.token_ledger()
            if ledger:
                store.flush_llm_ledger(replica_name, ledger)
        except Exception:
            pass
        try:
            if self._shipper is not None:
                self._shipper.free()
        except Exception:
            pass
        try:
            self.engine.stop()
        except Exception:
            pass

    def check_health(self):
        if not self.engine._thread.is_alive():
            raise RuntimeError("LLM engine thread died")
        return "ok"
