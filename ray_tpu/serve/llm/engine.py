"""Continuous-batching LLM engine: one per serve replica.

Reference analogue: vLLM's LLMEngine/Scheduler (the workload shape of
PAPERS.md arxiv 2605.25645, "Fine-Tuning and Serving Gemma 4 31B on
Google Cloud TPU"). The serve data plane's adaptive micro-batching
(PR 2) flushes a *window* of requests into one call — right for
stateless fns, wrong for autoregressive decode, where a batch admitted
together must otherwise run until its LONGEST member finishes while
finished slots sit idle. This engine schedules at token granularity:

* every engine step runs ONE batched decode over all RUNNING
  sequences; a sequence that finishes frees its KV pages and its batch
  slot **that step**, and a WAITING sequence takes the slot on the
  next step — no flush windows, no drain-the-batch stalls;
* admission is **prefill/decode cost-aware**: per step at most
  ``max_prefill_tokens`` of prompt work is attached to the decode
  batch (one over-budget prompt is admitted alone), so a long prefill
  can never starve the in-flight decode batch, and a sequence is only
  admitted when the paged KV cache can hold its prompt PLUS its full
  generation budget (no mid-decode OOM, ``kv_cache.py``);
* ``policy="static"`` keeps the same code path but only admits when
  the running set is empty — the flush-by-window baseline the
  ``_BENCH_LLM`` gate compares against.

Three fleet-efficiency features compose as engine flags
(docs/LLM_SERVING.md):

* ``enable_prefix_cache`` — admission looks the prompt up in a radix
  tree over KV pages (``prefix_cache.py``); cached prefix tokens are
  mapped read-only into the block table and skipped at prefill, with
  copy-on-extend when the suffix starts mid-page.  Cache-hit tokens
  flow into the ledger, metrics, and the autoscaler signal.
* ``spec_k`` — speculative decoding (``spec_decode.py``): a draft
  proposes up to k greedy tokens, the target verifies them in ONE
  batched ``decode_window`` step, greedy accept/reject keeps the
  output token-identical to sequential greedy decode.
* prefill/decode disaggregation — ``prefill_export`` runs prompt +
  first token on a prefill replica and snapshots the prompt's KV
  pages; ``adopt_request`` on a decode replica rebinds the shipped
  pages into fresh ones (``disagg.py`` carries them over plasmax) and
  the sequence enters the decode batch mid-flight.

Tokens stream out through per-sequence cursors (``poll``), which the
replica exposes as ``__llm_next__`` and the router/proxy turn into
handle iterators and SSE (docs/LLM_SERVING.md).

Drain (``prepare_drain``): stop admitting NEW sequences — shed them
retriably so the router places them on a serving replica — but finish
every in-flight decode; the replica reports running+waiting sequences
in its load so the controller's drain poll waits for zero before the
kill (KV-aware graceful drain).

Tracing: each sequence carries the trace ctx of its ``__llm_open__``
call; on finish the engine records ``llm.queue`` / ``llm.kv_alloc`` /
``llm.prefix_lookup`` / ``llm.prefill`` / ``llm.decode`` /
``llm.kv_ship`` / ``llm.draft`` / ``llm.verify`` phase spans, so
``ray-tpu trace critical-path`` attributes time-to-first-token vs
inter-token latency per request.
"""

from __future__ import annotations

import dataclasses
import math
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.serve.exceptions import ReplicaOverloadedError
from ray_tpu.serve.llm.kv_cache import OutOfKVBlocksError, PagedKVCache

# sequence states
WAITING, RUNNING, FINISHED, FAILED = ("waiting", "running", "finished",
                                      "failed")


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode controls (greedy by default — deterministic,
    the property the continuous-vs-static equivalence gate relies on).
    ``seed`` keys a per-request RNG so temperature sampling replays."""
    max_new_tokens: int = 32
    temperature: float = 0.0
    seed: int = 0
    stop_token: Optional[int] = None

    @classmethod
    def from_payload(cls, payload: Dict[str, Any]) -> "SamplingParams":
        return cls(
            max_new_tokens=max(1, int(payload.get("max_new_tokens", 32))),
            temperature=max(0.0, float(payload.get("temperature", 0.0))),
            seed=int(payload.get("seed", 0)),
            stop_token=payload.get("stop_token"))

    def to_payload(self) -> Dict[str, Any]:
        return {"max_new_tokens": self.max_new_tokens,
                "temperature": self.temperature,
                "seed": self.seed,
                "stop_token": self.stop_token}


@dataclass
class EngineConfig:
    max_running: int = 16          # decode batch slots
    max_waiting: int = 64          # admission queue bound (shed past it)
    max_prefill_tokens: int = 512  # prompt tokens attachable per step
    max_seq_len: int = 2048        # prompt + generation hard cap
    num_blocks: int = 512          # KV pool pages (+1 reserved null)
    block_size: int = 16           # tokens per page
    policy: str = "continuous"     # continuous | static
    enable_prefix_cache: bool = False   # radix prefix KV sharing
    spec_k: int = 0                # speculative draft tokens per step
    draft_model: Optional[str] = None        # toy | gpt2 | llama
    draft_model_config: Optional[Dict[str, Any]] = None


@dataclass
class Sequence:
    seq_id: str
    request_id: Optional[str]
    prompt: List[int]
    sampling: SamplingParams
    trace_ctx: Optional[Dict[str, str]] = None
    status: str = WAITING
    tokens: List[int] = field(default_factory=list)   # generated
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    # fleet features
    cached_tokens: int = 0          # prompt tokens skipped at prefill
    export_kv: bool = False         # prefill-role: snapshot KV on finish
    adopted: bool = False           # decode-role: arrived via handoff
    import_lane: Optional[str] = None
    draft_proposed: int = 0
    draft_accepted: int = 0
    draft_s: float = 0.0
    verify_s: float = 0.0
    # phase timestamps for spans + TTFT/ITL telemetry
    t_arrival: float = field(default_factory=time.time)
    t_alloc: Optional[float] = None
    t_prefill_start: Optional[float] = None
    t_prefill_end: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    t_import_start: Optional[float] = None
    t_import_end: Optional[float] = None
    rng: Optional[random.Random] = None

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.tokens)

    def budget_tokens(self) -> int:
        return len(self.prompt) + self.sampling.max_new_tokens


class LLMEngine:
    """Continuous-batching scheduler + paged KV cache + streaming
    cursors around one model adapter (``model_runner.py``)."""

    def __init__(self, adapter, config: Optional[EngineConfig] = None):
        self.adapter = adapter
        self.config = config or EngineConfig()
        self.cache = PagedKVCache(self.config.num_blocks,
                                  self.config.block_size)
        adapter.bind_cache(self.cache)
        self.prefix_cache = None
        if self.config.enable_prefix_cache:
            from ray_tpu.serve.llm.prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(self.cache)
        self._draft = None
        if self.config.spec_k > 0:
            from ray_tpu.serve.llm.spec_decode import make_draft
            self._draft = make_draft(
                self.config.draft_model or "toy",
                self.config.draft_model_config)
        self._seqs: Dict[str, Sequence] = {}
        self._waiting: deque = deque()          # seq ids, FIFO
        self._running: List[str] = []           # decode batch membership
        self._draining = False
        self._stopped = False
        self._seq_counter = 0
        self._lock = threading.Lock()
        self._work_cv = threading.Condition(self._lock)   # engine wakeup
        self._out_cv = threading.Condition(self._lock)    # pollers wakeup
        # telemetry: bounded reservoirs + a (ts, n) token-rate window
        self._ttft = deque(maxlen=512)
        self._itl = deque(maxlen=2048)
        self._rate_win: deque = deque()          # (ts, tokens committed)
        self._hit_win: deque = deque()           # (ts, cache-hit tokens)
        self._total_generated = 0
        self._total_prompt = 0
        self._total_requests = 0
        self._total_finished = 0
        self._total_shed = 0
        self._total_failed = 0
        self._total_cache_hit = 0       # finalized (ledger-consistent)
        self._total_draft = 0
        self._total_accepted = 0
        # prefill-role KV snapshots awaiting pickup (__llm_prefill__)
        self._exports: Dict[str, Dict[str, Any]] = {}
        # per-request token ledger:
        # (rid, n_tokens, finish_reason, n_prompt, n_cached) — the
        # server half of the game-day per-token reconciliation
        self._token_ledger = deque(maxlen=65536)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-llm-engine")
        self._thread.start()

    # ------------------------------------------------------------ intake

    def add_request(self, prompt_tokens: List[int],
                    sampling: Optional[SamplingParams] = None,
                    request_id: Optional[str] = None,
                    trace_ctx: Optional[Dict[str, str]] = None,
                    _export_kv: bool = False) -> str:
        """Enqueue a sequence; returns its stream id. Sheds retriably
        (``ReplicaOverloadedError``) when draining, when the waiting
        queue is full, or when the request can never fit the pool —
        the router re-places shed sequences on another replica."""
        sampling = sampling or SamplingParams()
        n_prompt = len(prompt_tokens)
        if n_prompt == 0:
            raise ValueError("empty prompt")
        if n_prompt + sampling.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({n_prompt}) + max_new_tokens "
                f"({sampling.max_new_tokens}) exceeds max_seq_len "
                f"{self.config.max_seq_len}")
        total = n_prompt + sampling.max_new_tokens
        if self.cache.blocks_for(total) > self.cache.num_blocks - 1:
            raise ValueError(
                f"request needs {self.cache.blocks_for(total)} KV blocks"
                f" but the pool only has {self.cache.num_blocks - 1}")
        with self._lock:
            if self._draining or self._stopped:
                self._total_shed += 1
                raise ReplicaOverloadedError(
                    "llm-engine(draining)", len(self._waiting),
                    self.config.max_waiting)
            if len(self._waiting) >= self.config.max_waiting:
                self._total_shed += 1
                raise ReplicaOverloadedError(
                    "llm-engine", len(self._waiting),
                    self.config.max_waiting)
            self._seq_counter += 1
            seq_id = f"seq-{self._seq_counter}"
            seq = Sequence(seq_id, request_id, list(prompt_tokens),
                           sampling, trace_ctx=trace_ctx)
            seq.export_kv = _export_kv
            if sampling.temperature > 0:
                seq.rng = random.Random(
                    (hash(request_id or seq_id) & 0xFFFFFFFF)
                    ^ sampling.seed)
            self._seqs[seq_id] = seq
            self._waiting.append(seq_id)
            self._total_requests += 1
            self._total_prompt += n_prompt
            self._work_cv.notify_all()
            return seq_id

    # ---- prefill/decode disaggregation (disagg.py, docs/LLM_SERVING) --

    def prefill_export(self, prompt_tokens: List[int],
                       sampling: Optional[SamplingParams] = None,
                       request_id: Optional[str] = None,
                       trace_ctx: Optional[Dict[str, str]] = None) -> str:
        """Prefill-role entry: run the prompt and exactly ONE decode
        step, snapshotting the prompt's KV pages on finish for
        shipment to a decode replica (``take_export``)."""
        sampling = sampling or SamplingParams()
        one = dataclasses.replace(sampling, max_new_tokens=1)
        return self.add_request(prompt_tokens, one, request_id,
                                trace_ctx, _export_kv=True)

    def take_export(self, seq_id: str,
                    max_wait_s: float = 5.0) -> Optional[Dict[str, Any]]:
        # the poller can observe ``done`` a beat before _retire stages
        # the snapshot — wait it out (bounded)
        deadline = time.time() + max(0.0, max_wait_s)
        with self._lock:
            while seq_id not in self._exports:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._out_cv.wait(timeout=min(remaining, 0.25))
            return self._exports.pop(seq_id, None)

    def adopt_request(self, prompt_tokens: List[int], first_token: int,
                      kv_blob: Optional[Dict[str, Any]],
                      sampling: Optional[SamplingParams] = None,
                      request_id: Optional[str] = None,
                      trace_ctx: Optional[Dict[str, str]] = None,
                      lane: str = "inline",
                      t_ship_start: Optional[float] = None) -> str:
        """Decode-role entry: rebind a shipped prompt KV snapshot into
        freshly allocated pages and enter the decode batch mid-flight —
        the first token is pollable immediately (disagg's TTFT win).

        Raises ``ReplicaOverloadedError`` (retriable) when the pool or
        batch is full, and whatever ``import_kv`` raises on a blob
        mismatch — the deployment falls back to plain ``add_request``
        (re-prefill) in both cases."""
        sampling = sampling or SamplingParams()
        n_prompt = len(prompt_tokens)
        if n_prompt == 0:
            raise ValueError("empty prompt")
        stop = sampling.stop_token
        terminal = None
        if stop is not None and int(first_token) == stop:
            terminal = "stop"
        elif sampling.max_new_tokens <= 1:
            terminal = "length"
        with self._lock:
            if self._draining or self._stopped:
                self._total_shed += 1
                raise ReplicaOverloadedError(
                    "llm-engine(draining)", len(self._waiting),
                    self.config.max_waiting)
            self._seq_counter += 1
            seq_id = f"seq-{self._seq_counter}"
            seq = Sequence(seq_id, request_id, list(prompt_tokens),
                           sampling, trace_ctx=trace_ctx)
            seq.adopted = True
            seq.cached_tokens = n_prompt    # zero prefill work here
            seq.import_lane = lane
            if sampling.temperature > 0:
                seq.rng = random.Random(
                    (hash(request_id or seq_id) & 0xFFFFFFFF)
                    ^ sampling.seed)
            self._seqs[seq_id] = seq
            self._total_requests += 1
            self._total_prompt += n_prompt
        if terminal is not None:
            # the prefill replica's single token already ended the
            # stream — no pages, no import, just a finished cursor
            with self._lock:
                now = time.time()
                seq.tokens = [int(first_token)]
                seq.t_first_token = now
                seq.t_finish = now
                seq.status = FINISHED
                seq.finish_reason = terminal
                self._total_generated += 1
                self._out_cv.notify_all()
            self._finalize(seq)
            return seq_id
        if terminal is None and kv_blob is None:
            raise ValueError("adopt_request needs a KV blob")
        budget = seq.budget_tokens()
        if n_prompt + sampling.max_new_tokens > self.config.max_seq_len:
            with self._lock:
                self._seqs.pop(seq_id, None)
            raise ValueError("prompt + max_new_tokens exceeds max_seq_len")
        try:
            try:
                self.cache.allocate(seq_id, budget)
            except OutOfKVBlocksError:
                if self.prefix_cache is None:
                    raise
                self.prefix_cache.evict(self.cache.blocks_for(budget))
                self.cache.allocate(seq_id, budget)
        except OutOfKVBlocksError as e:
            with self._lock:
                self._seqs.pop(seq_id, None)
                self._total_shed += 1
            raise ReplicaOverloadedError(
                "llm-engine(kv)", len(self._running),
                self.config.max_running) from e
        t_imp0 = time.time()
        try:
            self.adapter.import_kv(seq_id, n_prompt, kv_blob)
        except Exception:
            self.cache.free(seq_id)
            with self._lock:
                self._seqs.pop(seq_id, None)
            raise
        with self._lock:
            now = time.time()
            seq.t_alloc = t_imp0
            seq.t_import_start = t_ship_start or t_imp0
            seq.t_import_end = now
            seq.tokens = [int(first_token)]
            seq.t_first_token = now
            self._ttft.append(now - seq.t_arrival)
            self._total_generated += 1
            self._rate_win.append((now, 1))
            self._hit_win.append((now, n_prompt))
            seq.status = RUNNING
            self._running.append(seq_id)
            self._work_cv.notify_all()
            self._out_cv.notify_all()
        return seq_id

    def poll(self, seq_id: str, cursor: int = 0,
             max_wait_s: float = 10.0) -> Dict[str, Any]:
        """Streaming cursor read: block (bounded) until tokens past
        ``cursor`` exist or the sequence finished; returns the delta."""
        deadline = time.time() + max(0.0, max_wait_s)
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                raise KeyError(f"unknown stream {seq_id!r}")
            while (len(seq.tokens) <= cursor
                   and seq.status not in (FINISHED, FAILED)):
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._out_cv.wait(timeout=min(remaining, 1.0))
            done = seq.status in (FINISHED, FAILED)
            out = {
                "tokens": list(seq.tokens[cursor:]),
                "cursor": len(seq.tokens),
                "done": done,
                "n_tokens": len(seq.tokens),
            }
            if done:
                out["finish_reason"] = seq.finish_reason
                if seq.error:
                    out["error"] = seq.error
                if seq.t_first_token is not None:
                    out["ttft_s"] = round(
                        seq.t_first_token - seq.t_arrival, 6)
                # a finished, fully-read stream is garbage-collectable
                if cursor + len(out["tokens"]) >= len(seq.tokens):
                    self._seqs.pop(seq_id, None)
            return out

    def cancel(self, seq_id: str) -> bool:
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                return False
            if seq.status in (FINISHED, FAILED):
                self._seqs.pop(seq_id, None)
                return True
            if seq.status == WAITING:
                try:
                    self._waiting.remove(seq_id)
                except ValueError:
                    pass
            else:
                try:
                    self._running.remove(seq_id)
                except ValueError:
                    pass
                self.adapter.release(seq_id)
                self.cache.free(seq_id)
            seq.status = FAILED
            seq.finish_reason = "cancelled"
            seq.t_finish = time.time()
            self._seqs.pop(seq_id, None)
            self._out_cv.notify_all()
            return True

    # ------------------------------------------------------------ control

    def prepare_drain(self):
        """KV-aware drain step: no new sequences, in-flight ones run
        to completion (the controller kills the replica only once the
        reported queue — which includes these — hits zero)."""
        with self._lock:
            self._draining = True
            self._work_cv.notify_all()

    def stop(self):
        with self._lock:
            self._stopped = True
            self._work_cv.notify_all()
            self._out_cv.notify_all()
        self._thread.join(timeout=5.0)

    def in_flight(self) -> int:
        with self._lock:
            return len(self._running) + len(self._waiting)

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            now = time.time()
            while self._rate_win and now - self._rate_win[0][0] > 5.0:
                self._rate_win.popleft()
            while self._hit_win and now - self._hit_win[0][0] > 5.0:
                self._hit_win.popleft()
            window_tokens = sum(n for _, n in self._rate_win)
            window_s = (now - self._rate_win[0][0]
                        if len(self._rate_win) > 1 else 0.0)
            hit_tokens = sum(n for _, n in self._hit_win)
            ttft = sorted(self._ttft)
            itl = sorted(self._itl)

            def q(vals, frac):
                if not vals:
                    return 0.0
                return vals[min(len(vals) - 1, int(frac * len(vals)))]

            out = {
                "running": len(self._running),
                "waiting": len(self._waiting),
                "draining": self._draining,
                "tokens_per_s": round(
                    window_tokens / window_s, 3) if window_s > 0 else 0.0,
                "generated_tokens_total": self._total_generated,
                "prompt_tokens_total": self._total_prompt,
                "requests_total": self._total_requests,
                "finished_total": self._total_finished,
                "shed_total": self._total_shed,
                "failed_total": self._total_failed,
                "cache_hit_tokens_total": self._total_cache_hit,
                "cache_hit_tokens_per_s": round(
                    hit_tokens / window_s, 3) if window_s > 0 else 0.0,
                "spec_draft_tokens_total": self._total_draft,
                "spec_accepted_tokens_total": self._total_accepted,
                "ttft_p50_s": round(q(ttft, 0.50), 6),
                "ttft_p99_s": round(q(ttft, 0.99), 6),
                "itl_p50_s": round(q(itl, 0.50), 6),
                "itl_p99_s": round(q(itl, 0.99), 6),
            }
        out.update(self.cache.stats())
        if self.prefix_cache is not None:
            out.update(self.prefix_cache.stats())
        return out

    def token_ledger(self) -> List[Any]:
        """(request_id, n_tokens, finish_reason, n_prompt, n_cached)
        per finished sequence — joined against client-side token
        counts and prompt lengths by the game-day reconciler."""
        with self._lock:
            return [list(r) for r in self._token_ledger]

    # ------------------------------------------------------------ engine

    def _loop(self):
        while True:
            with self._lock:
                if self._stopped:
                    return
                if not self._running and not self._waiting:
                    self._work_cv.wait(timeout=0.5)
                    continue
            try:
                self._step()
            except Exception as e:  # noqa: BLE001 — fail sequences, not
                self._fail_all(e)   # the engine thread

    def _admit_locked(self) -> List[Sequence]:
        """Cost-aware admission (caller holds the lock): fill free
        batch slots from the FIFO while this step's prefill budget and
        the KV pool allow.  With the prefix cache on, the prompt is
        first matched against the radix tree: matched pages map in
        read-only (refcounted) and their tokens don't count against
        the prefill budget.  Static policy only admits into an empty
        batch (the flush-by-window baseline)."""
        if self.config.policy == "static" and self._running:
            return []
        admitted: List[Sequence] = []
        budget = self.config.max_prefill_tokens
        bs = self.cache.block_size
        while (self._waiting
               and len(self._running) + len(admitted)
               < self.config.max_running):
            seq = self._seqs[self._waiting[0]]
            n_prompt = len(seq.prompt)
            t0 = time.time()
            shared_pages: List[int] = []
            cached = 0
            if self.prefix_cache is not None:
                m, pages = self.prefix_cache.lookup(seq.prompt)
                # always recompute >= 1 prompt token so prefill has
                # logits to sample the first generated token from
                m = min(m, n_prompt - 1)
                if m > 0:
                    shared_pages = pages[:-(-m // bs)]
                    cached = m
            cost = n_prompt - cached
            if admitted and cost > budget:
                break  # next step; an over-budget prompt goes alone
            need_total = self.cache.blocks_for(seq.budget_tokens())
            try:
                try:
                    if shared_pages:
                        self.cache.allocate_with_prefix(
                            seq.seq_id, seq.budget_tokens(), shared_pages)
                    else:
                        self.cache.allocate(seq.seq_id,
                                            seq.budget_tokens())
                except OutOfKVBlocksError:
                    if self.prefix_cache is None:
                        raise
                    # recycle cold cached branches before giving up —
                    # never the prefix we just matched
                    freed = self.prefix_cache.evict(
                        need_total - len(shared_pages),
                        pinned=set(shared_pages))
                    if not freed:
                        raise
                    if shared_pages:
                        self.cache.allocate_with_prefix(
                            seq.seq_id, seq.budget_tokens(), shared_pages)
                    else:
                        self.cache.allocate(seq.seq_id,
                                            seq.budget_tokens())
                seq.t_alloc = time.time()
                seq._t_alloc_start = t0  # type: ignore[attr-defined]
            except OutOfKVBlocksError:
                break  # pages free up as running sequences finish
            seq.cached_tokens = cached
            if cached:
                self._hit_win.append((seq.t_alloc, cached))
            self._waiting.popleft()
            admitted.append(seq)
            budget -= cost
            if cost >= self.config.max_prefill_tokens:
                break  # the lone long prefill consumed the step
        return admitted

    def _step(self):
        """One engine step: decode every RUNNING sequence, then prefill
        this step's admissions (decode first — admission cost must
        never delay in-flight tokens)."""
        with self._lock:
            decode_seqs = [self._seqs[sid] for sid in self._running
                           if sid in self._seqs]
        if decode_seqs:
            if self._draft is not None:
                self._decode_spec(decode_seqs)
            else:
                self._decode(decode_seqs)
        with self._lock:
            admitted = self._admit_locked()
        if admitted:
            self._prefill(admitted)

    def _decode(self, seqs: List[Sequence]):
        t0 = time.time()
        logits = self.adapter.decode(seqs)      # [B, V] np.ndarray
        self._commit(seqs, logits, step_t0=t0)

    def _decode_spec(self, seqs: List[Sequence]):
        """Speculative step: draft proposes per greedy sequence, the
        target verifies every window in ONE batched decode_window
        call, accepted tokens commit together.  Non-greedy sequences
        ride the same step with single-token windows — composable with
        everything else."""
        t0 = time.time()
        k = self.config.spec_k
        vocab = getattr(self.adapter, "vocab_size", None)
        windows: List[List[int]] = []
        for s in seqs:
            remaining = s.sampling.max_new_tokens - len(s.tokens)
            if (s.sampling.temperature > 0 or remaining <= 1 or k <= 0):
                windows.append([int(s.tokens[-1])])
                continue
            w = min(k + 1, remaining + 1)
            td = time.time()
            props = self._draft.propose(s.prompt + s.tokens, w - 1)
            s.draft_s += time.time() - td
            win = [int(s.tokens[-1])]
            for p in props:
                p = int(p)
                if vocab is not None and not (0 <= p < vocab):
                    break   # draft vocab overhangs the target's
                win.append(p)
            s.draft_proposed += len(win) - 1
            self._total_draft += len(win) - 1
            windows.append(win)
        tv = time.time()
        rows = self.adapter.decode_window(seqs, windows)
        verify_dt = time.time() - tv
        self._commit_window(seqs, windows, rows, step_t0=t0,
                            verify_dt=verify_dt)

    def _prefill(self, seqs: List[Sequence]):
        t0 = time.time()
        for s in seqs:
            s.t_prefill_start = t0
        logits = self.adapter.prefill(seqs)     # [B, V]
        t1 = time.time()
        if self.prefix_cache is not None:
            # publish the finished prompts' full pages to the radix
            # tree (before _commit can free a finished seq's pages)
            for s in seqs:
                table = self.cache.block_table(s.seq_id)
                if table:
                    self.prefix_cache.insert(s.prompt, table)
        with self._lock:
            for s in seqs:
                s.t_prefill_end = t1
                s.status = RUNNING
                self._running.append(s.seq_id)
        self._commit(seqs, logits, step_t0=t0)

    def _sample(self, seq: Sequence, row) -> int:
        if seq.sampling.temperature <= 0 or seq.rng is None:
            return int(row.argmax())
        x = [v / seq.sampling.temperature for v in row.tolist()]
        m = max(x)
        exps = [math.exp(v - m) for v in x]
        total = sum(exps)
        r = seq.rng.random() * total
        acc = 0.0
        for i, e in enumerate(exps):
            acc += e
            if acc >= r:
                return i
        return len(exps) - 1

    def _finish_checks_locked(self, seq: Sequence, tok: int) -> bool:
        stop = seq.sampling.stop_token
        if stop is not None and tok == stop:
            seq.finish_reason = "stop"
        elif len(seq.tokens) >= seq.sampling.max_new_tokens:
            seq.finish_reason = "length"
        return seq.finish_reason is not None

    def _commit(self, seqs: List[Sequence], logits, *, step_t0: float):
        """Sample one token per sequence and publish: streaming
        cursors advance, finished sequences free their pages and their
        batch slot immediately (the admission the NEXT step sees)."""
        now = time.time()
        finished: List[Sequence] = []
        with self._lock:
            for i, seq in enumerate(seqs):
                sid = seq.seq_id
                if sid not in self._seqs or seq.status not in (RUNNING,
                                                               WAITING):
                    continue
                tok = self._sample(seq, logits[i])
                if seq.t_first_token is None:
                    seq.t_first_token = now
                    self._ttft.append(now - seq.t_arrival)
                else:
                    self._itl.append(now - step_t0)
                seq.tokens.append(tok)
                self._total_generated += 1
                if self._finish_checks_locked(seq, tok):
                    seq.status = FINISHED
                    seq.t_finish = now
                    try:
                        self._running.remove(sid)
                    except ValueError:
                        pass
                    finished.append(seq)
            self._rate_win.append((now, len(seqs)))
            self._out_cv.notify_all()
        self._retire(finished)

    def _commit_window(self, seqs: List[Sequence],
                       windows: List[List[int]], rows,
                       *, step_t0: float, verify_dt: float):
        """Speculative publish: per sequence, accept the drafted
        prefix the target agrees with (greedy_verify), commit the
        correction/bonus, and roll the KV cache back over rejected
        window positions."""
        from ray_tpu.serve.llm.spec_decode import greedy_verify
        now = time.time()
        finished: List[Sequence] = []
        rollbacks: List[tuple] = []
        total_committed = 0
        with self._lock:
            for seq, win, row in zip(seqs, windows, rows):
                sid = seq.seq_id
                if sid not in self._seqs or seq.status != RUNNING:
                    # cancelled mid-step: its state is already released
                    continue
                if len(win) == 1:
                    committed = [self._sample(seq, row[0])]
                else:
                    seq.verify_s += verify_dt / max(1, len(seqs))
                    argmaxes = [int(r.argmax()) for r in row]
                    committed = greedy_verify(win, argmaxes)
                    acc = max(0, len(committed) - 1)
                    seq.draft_accepted += acc
                    self._total_accepted += acc
                applied = 0
                dt_tok = (now - step_t0) / max(1, len(committed))
                for tok in committed:
                    if seq.t_first_token is None:
                        seq.t_first_token = now
                        self._ttft.append(now - seq.t_arrival)
                    else:
                        self._itl.append(dt_tok)
                    seq.tokens.append(int(tok))
                    applied += 1
                    self._total_generated += 1
                    if self._finish_checks_locked(seq, int(tok)):
                        break
                total_committed += applied
                # cache holds len(win) new positions; keep exactly the
                # ones a sequential decode would have written
                if applied < len(win):
                    rollbacks.append((sid, len(win) - applied))
                if seq.finish_reason:
                    seq.status = FINISHED
                    seq.t_finish = now
                    try:
                        self._running.remove(sid)
                    except ValueError:
                        pass
                    finished.append(seq)
            self._rate_win.append((now, total_committed))
            self._out_cv.notify_all()
        for sid, n in rollbacks:
            self.adapter.rollback(sid, n)
        self._retire(finished)

    def _retire(self, finished: List[Sequence]):
        for seq in finished:
            if seq.export_kv:
                self._maybe_export(seq)
            self.adapter.release(seq.seq_id)
            self.cache.free(seq.seq_id)
            self._finalize(seq)

    def _maybe_export(self, seq: Sequence):
        """Prefill-role finish: snapshot the prompt's KV pages BEFORE
        release/free recycles them; ``__llm_prefill__`` picks the
        snapshot up via ``take_export``."""
        try:
            blob = self.adapter.export_kv(seq.seq_id, len(seq.prompt))
        except Exception:
            blob = None
        with self._lock:
            self._exports[seq.seq_id] = {
                "prompt": list(seq.prompt),
                "first_token": seq.tokens[0] if seq.tokens else None,
                "kv": blob,
                "finish_reason": seq.finish_reason,
                "cached_tokens": seq.cached_tokens,
            }
            while len(self._exports) > 128:
                self._exports.pop(next(iter(self._exports)))
            self._out_cv.notify_all()

    def _finalize(self, seq: Sequence):
        with self._lock:
            self._total_finished += 1
            self._total_cache_hit += seq.cached_tokens
            reason = seq.finish_reason
            if seq.export_kv and reason == "length":
                reason = "handoff"   # generation continues elsewhere
            self._token_ledger.append(
                (seq.request_id, len(seq.tokens), reason,
                 len(seq.prompt), seq.cached_tokens))
        self._record_spans(seq)

    def _fail_all(self, err: Exception):
        """A model-step failure fails the sequences it was computing —
        pollers see an explicit error, never a silent truncation."""
        with self._lock:
            ids = list(self._running) + list(self._waiting)
            self._running.clear()
            self._waiting.clear()
            for sid in ids:
                seq = self._seqs.get(sid)
                if seq is None:
                    continue
                seq.status = FAILED
                seq.error = f"{type(err).__name__}: {err}"
                seq.finish_reason = "error"
                seq.t_finish = time.time()
                self._total_failed += 1
                self.adapter.release(sid)
                self.cache.free(sid)
            self._out_cv.notify_all()

    # ------------------------------------------------------------ tracing

    def _record_spans(self, seq: Sequence):
        """Phase spans for the PR 9 trace plane: queue / kv-alloc /
        prefix-lookup / prefill / decode (+ kv_ship for adopted
        sequences, draft/verify aggregates for speculative ones),
        parented under the ``__llm_open__`` call's replica execute
        span — TTFT = queue + kv_alloc + prefill, inter-token latency
        = decode / n_tokens."""
        ctx = seq.trace_ctx
        if not ctx or not ctx.get("trace_id"):
            return
        from ray_tpu._private import tracing
        tid, parent = ctx["trace_id"], ctx.get("span_id")

        def span(name, phase, t0, t1, attrs=None, min_width=None):
            if t0 is None or t1 is None:
                return
            if min_width is not None:
                t1 = max(t1, t0 + min_width)
            elif t1 - t0 <= 1e-5:
                return
            tracing.record_span(
                tid, tracing.new_span_id(), name,
                parent_span_id=parent, kind="serve.llm", phase=phase,
                start_ts=t0, end_ts=t1, attrs=attrs)

        alloc_start = getattr(seq, "_t_alloc_start", None)
        span("llm.queue", "queue", seq.t_arrival,
             alloc_start or seq.t_prefill_start)
        span("llm.kv_alloc", "schedule", alloc_start, seq.t_alloc)
        if seq.cached_tokens and not seq.adopted:
            # sub-µs radix walk: clamp so the span survives recording
            span("llm.prefix_lookup", "schedule", alloc_start,
                 seq.t_alloc, attrs={"cached_tokens": seq.cached_tokens},
                 min_width=2e-5)
        span("llm.prefill", "execute", seq.t_prefill_start,
             seq.t_prefill_end,
             attrs={"prompt_tokens": len(seq.prompt),
                    "cached_tokens": seq.cached_tokens})
        if seq.adopted:
            span("llm.kv_ship", "transfer", seq.t_import_start,
                 seq.t_import_end,
                 attrs={"prompt_tokens": len(seq.prompt),
                        "lane": seq.import_lane or "inline"},
                 min_width=2e-5)
        span("llm.decode", "execute", seq.t_first_token, seq.t_finish,
             attrs={"tokens": len(seq.tokens),
                    "finish_reason": seq.finish_reason})
        if seq.draft_proposed and seq.t_first_token is not None:
            span("llm.draft", "execute", seq.t_first_token,
                 seq.t_first_token + seq.draft_s,
                 attrs={"proposed": seq.draft_proposed,
                        "accepted": seq.draft_accepted},
                 min_width=2e-5)
            span("llm.verify", "execute", seq.t_first_token,
                 seq.t_first_token + seq.verify_s,
                 attrs={"proposed": seq.draft_proposed,
                        "accepted": seq.draft_accepted},
                 min_width=2e-5)
