"""Continuous-batching LLM engine: one per serve replica.

Reference analogue: vLLM's LLMEngine/Scheduler (the workload shape of
PAPERS.md arxiv 2605.25645, "Fine-Tuning and Serving Gemma 4 31B on
Google Cloud TPU"). The serve data plane's adaptive micro-batching
(PR 2) flushes a *window* of requests into one call — right for
stateless fns, wrong for autoregressive decode, where a batch admitted
together must otherwise run until its LONGEST member finishes while
finished slots sit idle. This engine schedules at token granularity:

* every engine step runs ONE batched decode over all RUNNING
  sequences; a sequence that finishes frees its KV pages and its batch
  slot **that step**, and a WAITING sequence takes the slot on the
  next step — no flush windows, no drain-the-batch stalls;
* admission is **prefill/decode cost-aware**: per step at most
  ``max_prefill_tokens`` of prompt work is attached to the decode
  batch (one over-budget prompt is admitted alone), so a long prefill
  can never starve the in-flight decode batch, and a sequence is only
  admitted when the paged KV cache can hold its prompt PLUS its full
  generation budget (no mid-decode OOM, ``kv_cache.py``);
* ``policy="static"`` keeps the same code path but only admits when
  the running set is empty — the flush-by-window baseline the
  ``_BENCH_LLM`` gate compares against.

Tokens stream out through per-sequence cursors (``poll``), which the
replica exposes as ``__llm_next__`` and the router/proxy turn into
handle iterators and SSE (docs/LLM_SERVING.md).

Drain (``prepare_drain``): stop admitting NEW sequences — shed them
retriably so the router places them on a serving replica — but finish
every in-flight decode; the replica reports running+waiting sequences
in its load so the controller's drain poll waits for zero before the
kill (KV-aware graceful drain).

Tracing: each sequence carries the trace ctx of its ``__llm_open__``
call; on finish the engine records ``llm.queue`` / ``llm.kv_alloc`` /
``llm.prefill`` / ``llm.decode`` phase spans, so
``ray-tpu trace critical-path`` attributes time-to-first-token vs
inter-token latency per request.
"""

from __future__ import annotations

import math
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.serve.exceptions import ReplicaOverloadedError
from ray_tpu.serve.llm.kv_cache import OutOfKVBlocksError, PagedKVCache

# sequence states
WAITING, RUNNING, FINISHED, FAILED = ("waiting", "running", "finished",
                                      "failed")


@dataclass(frozen=True)
class SamplingParams:
    """Per-request decode controls (greedy by default — deterministic,
    the property the continuous-vs-static equivalence gate relies on).
    ``seed`` keys a per-request RNG so temperature sampling replays."""
    max_new_tokens: int = 32
    temperature: float = 0.0
    seed: int = 0
    stop_token: Optional[int] = None

    @classmethod
    def from_payload(cls, payload: Dict[str, Any]) -> "SamplingParams":
        return cls(
            max_new_tokens=max(1, int(payload.get("max_new_tokens", 32))),
            temperature=max(0.0, float(payload.get("temperature", 0.0))),
            seed=int(payload.get("seed", 0)),
            stop_token=payload.get("stop_token"))


@dataclass
class EngineConfig:
    max_running: int = 16          # decode batch slots
    max_waiting: int = 64          # admission queue bound (shed past it)
    max_prefill_tokens: int = 512  # prompt tokens attachable per step
    max_seq_len: int = 2048        # prompt + generation hard cap
    num_blocks: int = 512          # KV pool pages (+1 reserved null)
    block_size: int = 16           # tokens per page
    policy: str = "continuous"     # continuous | static


@dataclass
class Sequence:
    seq_id: str
    request_id: Optional[str]
    prompt: List[int]
    sampling: SamplingParams
    trace_ctx: Optional[Dict[str, str]] = None
    status: str = WAITING
    tokens: List[int] = field(default_factory=list)   # generated
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    # phase timestamps for spans + TTFT/ITL telemetry
    t_arrival: float = field(default_factory=time.time)
    t_alloc: Optional[float] = None
    t_prefill_start: Optional[float] = None
    t_prefill_end: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    rng: Optional[random.Random] = None

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.tokens)

    def budget_tokens(self) -> int:
        return len(self.prompt) + self.sampling.max_new_tokens


class LLMEngine:
    """Continuous-batching scheduler + paged KV cache + streaming
    cursors around one model adapter (``model_runner.py``)."""

    def __init__(self, adapter, config: Optional[EngineConfig] = None):
        self.adapter = adapter
        self.config = config or EngineConfig()
        self.cache = PagedKVCache(self.config.num_blocks,
                                  self.config.block_size)
        adapter.bind_cache(self.cache)
        self._seqs: Dict[str, Sequence] = {}
        self._waiting: deque = deque()          # seq ids, FIFO
        self._running: List[str] = []           # decode batch membership
        self._draining = False
        self._stopped = False
        self._seq_counter = 0
        self._lock = threading.Lock()
        self._work_cv = threading.Condition(self._lock)   # engine wakeup
        self._out_cv = threading.Condition(self._lock)    # pollers wakeup
        # telemetry: bounded reservoirs + a (ts, n) token-rate window
        self._ttft = deque(maxlen=512)
        self._itl = deque(maxlen=2048)
        self._rate_win: deque = deque()          # (ts, tokens committed)
        self._total_generated = 0
        self._total_prompt = 0
        self._total_requests = 0
        self._total_finished = 0
        self._total_shed = 0
        self._total_failed = 0
        # per-request token ledger: (rid, n_tokens, finish_reason) —
        # the server half of the game-day per-token reconciliation
        self._token_ledger = deque(maxlen=65536)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-llm-engine")
        self._thread.start()

    # ------------------------------------------------------------ intake

    def add_request(self, prompt_tokens: List[int],
                    sampling: Optional[SamplingParams] = None,
                    request_id: Optional[str] = None,
                    trace_ctx: Optional[Dict[str, str]] = None) -> str:
        """Enqueue a sequence; returns its stream id. Sheds retriably
        (``ReplicaOverloadedError``) when draining, when the waiting
        queue is full, or when the request can never fit the pool —
        the router re-places shed sequences on another replica."""
        sampling = sampling or SamplingParams()
        n_prompt = len(prompt_tokens)
        if n_prompt == 0:
            raise ValueError("empty prompt")
        if n_prompt + sampling.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({n_prompt}) + max_new_tokens "
                f"({sampling.max_new_tokens}) exceeds max_seq_len "
                f"{self.config.max_seq_len}")
        total = n_prompt + sampling.max_new_tokens
        if self.cache.blocks_for(total) > self.cache.num_blocks - 1:
            raise ValueError(
                f"request needs {self.cache.blocks_for(total)} KV blocks"
                f" but the pool only has {self.cache.num_blocks - 1}")
        with self._lock:
            if self._draining or self._stopped:
                self._total_shed += 1
                raise ReplicaOverloadedError(
                    "llm-engine(draining)", len(self._waiting),
                    self.config.max_waiting)
            if len(self._waiting) >= self.config.max_waiting:
                self._total_shed += 1
                raise ReplicaOverloadedError(
                    "llm-engine", len(self._waiting),
                    self.config.max_waiting)
            self._seq_counter += 1
            seq_id = f"seq-{self._seq_counter}"
            seq = Sequence(seq_id, request_id, list(prompt_tokens),
                           sampling, trace_ctx=trace_ctx)
            if sampling.temperature > 0:
                seq.rng = random.Random(
                    (hash(request_id or seq_id) & 0xFFFFFFFF)
                    ^ sampling.seed)
            self._seqs[seq_id] = seq
            self._waiting.append(seq_id)
            self._total_requests += 1
            self._total_prompt += n_prompt
            self._work_cv.notify_all()
            return seq_id

    def poll(self, seq_id: str, cursor: int = 0,
             max_wait_s: float = 10.0) -> Dict[str, Any]:
        """Streaming cursor read: block (bounded) until tokens past
        ``cursor`` exist or the sequence finished; returns the delta."""
        deadline = time.time() + max(0.0, max_wait_s)
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                raise KeyError(f"unknown stream {seq_id!r}")
            while (len(seq.tokens) <= cursor
                   and seq.status not in (FINISHED, FAILED)):
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._out_cv.wait(timeout=min(remaining, 1.0))
            done = seq.status in (FINISHED, FAILED)
            out = {
                "tokens": list(seq.tokens[cursor:]),
                "cursor": len(seq.tokens),
                "done": done,
                "n_tokens": len(seq.tokens),
            }
            if done:
                out["finish_reason"] = seq.finish_reason
                if seq.error:
                    out["error"] = seq.error
                if seq.t_first_token is not None:
                    out["ttft_s"] = round(
                        seq.t_first_token - seq.t_arrival, 6)
                # a finished, fully-read stream is garbage-collectable
                if cursor + len(out["tokens"]) >= len(seq.tokens):
                    self._seqs.pop(seq_id, None)
            return out

    def cancel(self, seq_id: str) -> bool:
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                return False
            if seq.status in (FINISHED, FAILED):
                self._seqs.pop(seq_id, None)
                return True
            if seq.status == WAITING:
                try:
                    self._waiting.remove(seq_id)
                except ValueError:
                    pass
            else:
                try:
                    self._running.remove(seq_id)
                except ValueError:
                    pass
                self.adapter.release(seq_id)
                self.cache.free(seq_id)
            seq.status = FAILED
            seq.finish_reason = "cancelled"
            seq.t_finish = time.time()
            self._seqs.pop(seq_id, None)
            self._out_cv.notify_all()
            return True

    # ------------------------------------------------------------ control

    def prepare_drain(self):
        """KV-aware drain step: no new sequences, in-flight ones run
        to completion (the controller kills the replica only once the
        reported queue — which includes these — hits zero)."""
        with self._lock:
            self._draining = True
            self._work_cv.notify_all()

    def stop(self):
        with self._lock:
            self._stopped = True
            self._work_cv.notify_all()
            self._out_cv.notify_all()
        self._thread.join(timeout=5.0)

    def in_flight(self) -> int:
        with self._lock:
            return len(self._running) + len(self._waiting)

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            now = time.time()
            while self._rate_win and now - self._rate_win[0][0] > 5.0:
                self._rate_win.popleft()
            window_tokens = sum(n for _, n in self._rate_win)
            window_s = (now - self._rate_win[0][0]
                        if len(self._rate_win) > 1 else 0.0)
            ttft = sorted(self._ttft)
            itl = sorted(self._itl)

            def q(vals, frac):
                if not vals:
                    return 0.0
                return vals[min(len(vals) - 1, int(frac * len(vals)))]

            out = {
                "running": len(self._running),
                "waiting": len(self._waiting),
                "draining": self._draining,
                "tokens_per_s": round(
                    window_tokens / window_s, 3) if window_s > 0 else 0.0,
                "generated_tokens_total": self._total_generated,
                "prompt_tokens_total": self._total_prompt,
                "requests_total": self._total_requests,
                "finished_total": self._total_finished,
                "shed_total": self._total_shed,
                "failed_total": self._total_failed,
                "ttft_p50_s": round(q(ttft, 0.50), 6),
                "ttft_p99_s": round(q(ttft, 0.99), 6),
                "itl_p50_s": round(q(itl, 0.50), 6),
                "itl_p99_s": round(q(itl, 0.99), 6),
            }
        out.update(self.cache.stats())
        return out

    def token_ledger(self) -> List[Any]:
        """(request_id, n_tokens, finish_reason) per finished sequence
        — joined against client-side token counts by the game-day
        reconciler."""
        with self._lock:
            return [list(r) for r in self._token_ledger]

    # ------------------------------------------------------------ engine

    def _loop(self):
        while True:
            with self._lock:
                if self._stopped:
                    return
                if not self._running and not self._waiting:
                    self._work_cv.wait(timeout=0.5)
                    continue
            try:
                self._step()
            except Exception as e:  # noqa: BLE001 — fail sequences, not
                self._fail_all(e)   # the engine thread

    def _admit_locked(self) -> List[Sequence]:
        """Cost-aware admission (caller holds the lock): fill free
        batch slots from the FIFO while this step's prefill budget and
        the KV pool allow. Static policy only admits into an empty
        batch (the flush-by-window baseline)."""
        if self.config.policy == "static" and self._running:
            return []
        admitted: List[Sequence] = []
        budget = self.config.max_prefill_tokens
        while (self._waiting
               and len(self._running) + len(admitted)
               < self.config.max_running):
            seq = self._seqs[self._waiting[0]]
            n_prompt = len(seq.prompt)
            if admitted and n_prompt > budget:
                break  # next step; an over-budget prompt goes alone
            try:
                t0 = time.time()
                self.cache.allocate(seq.seq_id, seq.budget_tokens())
                seq.t_alloc = time.time()
                seq._t_alloc_start = t0  # type: ignore[attr-defined]
            except OutOfKVBlocksError:
                break  # pages free up as running sequences finish
            self._waiting.popleft()
            admitted.append(seq)
            budget -= n_prompt
            if n_prompt >= self.config.max_prefill_tokens:
                break  # the lone long prefill consumed the step
        return admitted

    def _step(self):
        """One engine step: decode every RUNNING sequence, then prefill
        this step's admissions (decode first — admission cost must
        never delay in-flight tokens)."""
        with self._lock:
            decode_seqs = [self._seqs[sid] for sid in self._running
                           if sid in self._seqs]
        if decode_seqs:
            self._decode(decode_seqs)
        with self._lock:
            admitted = self._admit_locked()
        if admitted:
            self._prefill(admitted)

    def _decode(self, seqs: List[Sequence]):
        t0 = time.time()
        logits = self.adapter.decode(seqs)      # [B, V] np.ndarray
        self._commit(seqs, logits, step_t0=t0)

    def _prefill(self, seqs: List[Sequence]):
        t0 = time.time()
        for s in seqs:
            s.t_prefill_start = t0
        logits = self.adapter.prefill(seqs)     # [B, V]
        t1 = time.time()
        with self._lock:
            for s in seqs:
                s.t_prefill_end = t1
                s.status = RUNNING
                self._running.append(s.seq_id)
        self._commit(seqs, logits, step_t0=t0)

    def _sample(self, seq: Sequence, row) -> int:
        if seq.sampling.temperature <= 0 or seq.rng is None:
            return int(row.argmax())
        x = [v / seq.sampling.temperature for v in row.tolist()]
        m = max(x)
        exps = [math.exp(v - m) for v in x]
        total = sum(exps)
        r = seq.rng.random() * total
        acc = 0.0
        for i, e in enumerate(exps):
            acc += e
            if acc >= r:
                return i
        return len(exps) - 1

    def _commit(self, seqs: List[Sequence], logits, *, step_t0: float):
        """Sample one token per sequence and publish: streaming
        cursors advance, finished sequences free their pages and their
        batch slot immediately (the admission the NEXT step sees)."""
        now = time.time()
        finished: List[Sequence] = []
        with self._lock:
            for i, seq in enumerate(seqs):
                sid = seq.seq_id
                if sid not in self._seqs or seq.status not in (RUNNING,
                                                               WAITING):
                    continue
                tok = self._sample(seq, logits[i])
                if seq.t_first_token is None:
                    seq.t_first_token = now
                    self._ttft.append(now - seq.t_arrival)
                else:
                    self._itl.append(now - step_t0)
                seq.tokens.append(tok)
                self._total_generated += 1
                stop = seq.sampling.stop_token
                if stop is not None and tok == stop:
                    seq.finish_reason = "stop"
                elif len(seq.tokens) >= seq.sampling.max_new_tokens:
                    seq.finish_reason = "length"
                if seq.finish_reason:
                    seq.status = FINISHED
                    seq.t_finish = now
                    try:
                        self._running.remove(sid)
                    except ValueError:
                        pass
                    finished.append(seq)
            self._rate_win.append((now, len(seqs)))
            self._out_cv.notify_all()
        for seq in finished:
            self.adapter.release(seq.seq_id)
            self.cache.free(seq.seq_id)
            self._finalize(seq)

    def _finalize(self, seq: Sequence):
        with self._lock:
            self._total_finished += 1
            self._token_ledger.append(
                (seq.request_id, len(seq.tokens), seq.finish_reason))
        self._record_spans(seq)

    def _fail_all(self, err: Exception):
        """A model-step failure fails the sequences it was computing —
        pollers see an explicit error, never a silent truncation."""
        with self._lock:
            ids = list(self._running) + list(self._waiting)
            self._running.clear()
            self._waiting.clear()
            for sid in ids:
                seq = self._seqs.get(sid)
                if seq is None:
                    continue
                seq.status = FAILED
                seq.error = f"{type(err).__name__}: {err}"
                seq.finish_reason = "error"
                seq.t_finish = time.time()
                self._total_failed += 1
                self.adapter.release(sid)
                self.cache.free(sid)
            self._out_cv.notify_all()

    # ------------------------------------------------------------ tracing

    def _record_spans(self, seq: Sequence):
        """Phase spans for the PR 9 trace plane: queue / kv-alloc /
        prefill / decode, parented under the ``__llm_open__`` call's
        replica execute span — TTFT = queue + kv_alloc + prefill,
        inter-token latency = decode / n_tokens."""
        ctx = seq.trace_ctx
        if not ctx or not ctx.get("trace_id"):
            return
        from ray_tpu._private import tracing
        tid, parent = ctx["trace_id"], ctx.get("span_id")

        def span(name, phase, t0, t1, attrs=None):
            if t0 is None or t1 is None or t1 - t0 <= 1e-5:
                return
            tracing.record_span(
                tid, tracing.new_span_id(), name,
                parent_span_id=parent, kind="serve.llm", phase=phase,
                start_ts=t0, end_ts=t1, attrs=attrs)

        alloc_start = getattr(seq, "_t_alloc_start", None)
        span("llm.queue", "queue", seq.t_arrival,
             alloc_start or seq.t_prefill_start)
        span("llm.kv_alloc", "schedule", alloc_start, seq.t_alloc)
        span("llm.prefill", "execute", seq.t_prefill_start,
             seq.t_prefill_end,
             attrs={"prompt_tokens": len(seq.prompt)})
        span("llm.decode", "execute", seq.t_first_token, seq.t_finish,
             attrs={"tokens": len(seq.tokens),
                    "finish_reason": seq.finish_reason})
