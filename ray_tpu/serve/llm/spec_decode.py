"""Speculative decoding: a small draft proposes, the target verifies.

Greedy speculative decoding turns k sequential target decode steps
into one batched step without changing a single output token:

1. a cheap *draft* model proposes ``k`` greedy continuations,
2. the target runs ONE multi-token incremental step over
   ``[t_last, p_1 .. p_k]`` through the paged decode path
   (``adapter.decode_window``) — position ``j``'s logits condition on
   exactly the window prefix, because ``cached_attention`` is causal
   at the offset,
3. ``greedy_verify`` walks the target's argmaxes: a proposal is
   accepted while it equals what greedy decode *would* have emitted;
   the first mismatch is replaced by the target's own token
   (correction), and a fully accepted window yields one extra target
   token for free (bonus).

Acceptance therefore commits exactly the token sequence sequential
greedy decode produces — token-for-token identity is a theorem, not a
tuning goal; the tests in tests/test_llm_fleet.py assert it for the
toy model, gpt2, and llama (with a gpt2 draft — both tiny configs
share a 512-token vocab).

Drafts are *stateless* (no paged cache): the toy draft replays the
toy adapter's closed-form logits; the flax draft runs a full
non-incremental forward per proposed token, which is the right
trade for tiny draft models and keeps the KV pool untouched by
speculation.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def greedy_verify(window: Sequence[int],
                  argmax_tokens: Sequence[int]) -> List[int]:
    """Accept/reject a speculative window.

    ``window`` is ``[t_last, p_1 .. p_{w-1}]`` (last committed token
    followed by draft proposals); ``argmax_tokens[j]`` is the target's
    greedy token after consuming ``window[:j+1]``.  Returns the tokens
    to commit: the accepted proposals, then either the target's
    correction at the first mismatch or — if every proposal matched —
    the bonus token after the full window.
    """
    committed: List[int] = []
    for j in range(len(window)):
        t = int(argmax_tokens[j])
        committed.append(t)
        if j + 1 < len(window) and int(window[j + 1]) != t:
            break
    return committed


class ToyDraft:
    """Greedy draft mirroring ``ToyAdapter``'s closed-form LM (next
    token = argmax(mean(embed[prefix]) @ E^T)) — cache-free, so it can
    draft for any toy target; seed it like the target for a
    high-acceptance draft or differently for an adversarial one."""

    def __init__(self, vocab_size: int = 256, dim: int = 32,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self.vocab_size = int(vocab_size)
        self.embed = rng.randn(self.vocab_size, int(dim)).astype(
            np.float32)

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        toks = [int(t) % self.vocab_size for t in tokens]
        out: List[int] = []
        acc = self.embed[toks].sum(axis=0)
        for _ in range(int(k)):
            h = acc / len(toks)
            t = int(np.argmax(h @ self.embed.T))
            out.append(t)
            toks.append(t)
            acc = acc + self.embed[t]
        return out


class FlaxDraft:
    """Greedy draft over a (tiny) gpt2/llama checkpoint: one full
    non-incremental forward per proposed token, jitted per padded
    length bucket.  No paged cache — speculation never touches the
    target's KV pool."""

    def __init__(self, kind: str = "gpt2", config=None,
                 params=None, seed: int = 0):
        import jax
        import jax.numpy as jnp
        self._jnp = jnp
        self.kind = kind
        if kind == "gpt2":
            from ray_tpu.models import gpt2
            self.cfg = config or gpt2.GPT2Config.tiny()
            self.model = gpt2.GPT2(self.cfg)
        elif kind == "llama":
            from ray_tpu.models import llama
            self.cfg = config or llama.LlamaConfig.tiny()
            self.model = llama.LlamaModel(self.cfg)
        else:
            raise ValueError(f"unknown draft kind {kind!r}")
        self.vocab_size = self.cfg.vocab_size
        if params is None:
            dummy = jnp.zeros((1, 8), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed), dummy)
        self.params = params
        self._fns: Dict[int, Any] = {}

    def _fn(self, S: int):
        fn = self._fns.get(S)
        if fn is not None:
            return fn
        import jax

        def fwd(params, tokens):
            return self.model.apply(params, tokens)

        fn = jax.jit(fwd)
        self._fns[S] = fn
        return fn

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        jnp = self._jnp
        toks = [int(t) for t in tokens]
        out: List[int] = []
        max_pos = getattr(self.cfg, "n_positions",
                          getattr(self.cfg, "max_seq_len", 2048))
        for _ in range(int(k)):
            n = len(toks)
            if n >= max_pos:
                break
            S = 8
            while S < n:
                S *= 2
            S = min(S, max_pos)
            padded = np.zeros((1, S), np.int32)
            padded[0, :n] = toks
            # causal attention: positions < n never see the padding
            logits = self._fn(S)(self.params, jnp.asarray(padded))
            t = int(np.argmax(np.asarray(logits[0, n - 1])))
            out.append(t)
            toks.append(t)
        return out


def make_draft(model: str = "toy",
               model_config: Optional[Dict[str, Any]] = None):
    """Engine-facing factory mirroring ``make_adapter``: ``model`` is
    ``toy`` | ``gpt2`` | ``llama``."""
    model_config = dict(model_config or {})
    if model == "toy":
        return ToyDraft(**model_config)
    if model in ("gpt2", "llama"):
        return FlaxDraft(kind=model, **model_config)
    raise ValueError(f"unknown draft model {model!r} (toy|gpt2|llama)")
