"""Prefill/decode disaggregation: ship prompt KV pages between replicas.

Long prefills steal decode steps: a replica mid-way through a 2k-token
prompt cannot emit tokens for its in-flight streams.  Disaggregation
splits the fleet — *prefill* replicas run prompt + first token,
*decode* replicas run everything after — so decode inter-token latency
stops depending on the prompt-length tail.  The controller assigns
roles over a deployment's replicas (``llm_roles`` in
``serve.deployment``), the router runs the two-hop admission
(``__llm_prefill__`` on a prefill replica, ``__llm_adopt__`` on a
decode replica), and this module moves the KV snapshot between them.

Transport reuses the compiled-DAG plasmax machinery (dag/channel.py):
each prefill replica owns a small ring of fixed-size plasmax slots
(sealed shared-memory frames, one copy out on the reader side), with
inline bytes as the always-correct fallback when the store is absent
(unit tests), the snapshot outgrows a slot, or the ring is wedged.
Every handoff carries a CRC so a torn or corrupted frame is *detected*
and downgraded to a decode-side re-prefill — greedy decode is
deterministic, so the fallback is output-identical, just slower.

Chaos site ``llm.kv_ship`` (drop / delay / reset / corrupt) fires on
the receive side, mid-handoff: ``receive`` returns ``None`` (drop,
corrupt → CRC mismatch) or raises ``KVShipError`` (reset), and the
deployment falls back to re-prefill with no leaked pages — the blob is
plain bytes at this point; no allocator state is in flight.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
import zlib
from typing import Any, Dict, Optional

from ray_tpu._private import chaos, serialization
from ray_tpu.common.ids import ObjectID

logger = logging.getLogger(__name__)

CHAOS_SITE = "llm.kv_ship"
_INLINE_MAX = 64 * 1024        # below this, shared memory doesn't pay


class KVShipError(Exception):
    """The handoff frame was lost or torn mid-flight; the caller
    re-prefills on the decode replica (output-identical fallback)."""


def _ring_slot_id(tag: str, slot: int) -> ObjectID:
    digest = hashlib.sha256(f"llmkv:{tag}:{slot}".encode()).digest()
    return ObjectID(digest[:ObjectID.SIZE])


def _plasma():
    try:
        from ray_tpu._private import worker as worker_mod
        w = worker_mod._global_worker
        if w is not None and w.connected:
            return w.plasma
    except Exception:
        pass
    return None


class KVShipper:
    """One prefill replica's outbound KV lane (and any replica's
    inbound decoder).

    ``ship`` serializes a handoff payload (prompt KV arrays + enough
    metadata to re-prefill) into a plasmax ring slot — or inline bytes
    — and returns a frame descriptor; ``receive`` reverses it, with
    CRC verification and the ``llm.kv_ship`` chaos site in the middle.
    Frame descriptors are plain dicts so they ride the existing actor
    RPC path.
    """

    def __init__(self, tag: str, nslots: int = 4,
                 slot_bytes: int = 8 << 20):
        self.tag = tag
        self.nslots = max(1, int(nslots))
        self.slot_bytes = int(slot_bytes)
        self._created: Dict[int, ObjectID] = {}
        self._seq = 0
        self._lock = threading.Lock()

    # ---- prefill side ----

    def ship(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Serialize ``payload`` and stage it for the decode replica.
        Returns ``{"lane", "crc", "n", "o"|"b"}``."""
        ser = serialization.serialize(payload)
        data = ser.to_bytes()
        crc = zlib.crc32(data) & 0xFFFFFFFF
        desc: Dict[str, Any] = {"crc": crc, "n": len(data)}
        plasma = _plasma()
        if plasma is not None and len(data) > _INLINE_MAX \
                and len(data) <= self.slot_bytes:
            oid = self._write_ring(plasma, data)
            if oid is not None:
                desc["lane"] = "plasmax"
                desc["o"] = oid.hex()
                return desc
        desc["lane"] = "inline"
        desc["b"] = data
        return desc

    def _write_ring(self, plasma, data: bytes) -> Optional[ObjectID]:
        with self._lock:
            slot = self._seq % self.nslots
            self._seq += 1
            oid = self._created.get(slot)
            try:
                if oid is None:
                    oid = _ring_slot_id(self.tag, slot)
                    buf = plasma.ring_create(oid, self.slot_bytes)
                    self._created[slot] = oid
                else:
                    buf = plasma.ring_recycle(oid)
                    if buf is None:
                        return None   # reader wedged: inline this one
                    buf = buf[:self.slot_bytes]
            except Exception:
                return None   # store pressure etc.: inline is correct
            buf[:len(data)] = data
            buf.release()
            plasma.ring_seal(oid)
            return oid

    # ---- decode side ----

    def receive(self, desc: Dict[str, Any],
                method: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Fetch + verify a handoff frame.  Returns the payload dict,
        or ``None`` when the frame was dropped or failed its CRC
        (caller re-prefills); raises ``KVShipError`` on reset."""
        data = self._fetch(desc)
        action = chaos.hit(CHAOS_SITE, method=method)
        if action is not None:
            op = action.get("op")
            if op == "drop":
                logger.warning("llm.kv_ship: chaos dropped a handoff "
                               "frame (falling back to re-prefill)")
                return None
            if op == "delay":
                time.sleep(float(action.get("delay_s", 0.05)))
            elif op == "reset":
                raise KVShipError("llm.kv_ship: chaos reset mid-handoff")
            elif op == "corrupt" and data:
                data = bytearray(data)
                data[len(data) // 2] ^= 0xFF
                data = bytes(data)
        if data is None:
            return None
        if (zlib.crc32(data) & 0xFFFFFFFF) != desc.get("crc"):
            logger.warning("llm.kv_ship: CRC mismatch on handoff frame "
                           "(falling back to re-prefill)")
            return None
        try:
            return serialization.deserialize(data)
        except Exception:
            logger.warning("llm.kv_ship: undecodable handoff frame",
                           exc_info=True)
            return None

    def _fetch(self, desc: Dict[str, Any]) -> Optional[bytes]:
        if desc.get("o") is not None:
            plasma = _plasma()
            if plasma is None:
                return None
            oid = ObjectID.from_hex(desc["o"])
            buf = plasma.get_buffer(oid)
            if buf is None:
                return None   # slot vanished (ring freed/evicted)
            try:
                # copy out so the slot recycles immediately
                return bytes(buf[:desc["n"]])
            finally:
                buf.release()
                plasma.release(oid)
        b = desc.get("b")
        return bytes(b) if b is not None else None

    def free(self):
        plasma = _plasma()
        with self._lock:
            if plasma is not None:
                for oid in self._created.values():
                    try:
                        plasma.ring_free(oid)
                    except Exception:
                        logger.debug("llm.kv_ship: ring_free of %s "
                                     "failed (ring torn down first?)",
                                     oid, exc_info=True)
            self._created.clear()
