"""Paged (block) KV cache: the allocator behind continuous batching.

Reference analogue: vLLM's BlockSpaceManager (PAPERS.md "Fine-Tuning
and Serving Gemma 4 31B on Google Cloud TPU" serves through the same
design). The cache is a fixed pool of fixed-size pages; each sequence
owns a *block table* mapping its logical token positions to physical
pages. Growing a sequence by one token allocates at most one page;
finishing a sequence returns all its pages to the free list instantly.
Admission control is therefore exact: a prompt of L tokens with a
budget of G generated tokens needs ``ceil((L + G) / block_size)``
pages, and the engine refuses to admit what it cannot finish —
sequences never deadlock mid-decode waiting for pages.

Page 0 is reserved as the *null page*: batch-padding rows point every
block-table entry at it, so padded jit steps scatter their garbage
into scratch instead of a live sequence's memory.

The pool itself is storage-agnostic (``make_pages`` builds numpy or
jax arrays per layer on demand) — the allocator tracks only indices,
so the same bookkeeping serves the numpy toy adapter and the jitted
flax adapters.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class OutOfKVBlocksError(Exception):
    """The pool cannot satisfy an allocation — the engine keeps the
    sequence WAITING (or sheds it) rather than admitting work it
    cannot finish."""


class PagedKVCache:
    """Block allocator + occupancy accounting for one replica's pool.

    Thread-safe: the engine thread allocates/frees while actor threads
    read occupancy for admission and telemetry.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (page 0 is reserved)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # page 0 reserved as the null/scratch page for padding rows
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._tables: Dict[str, List[int]] = {}   # seq id -> pages
        self._lock = threading.Lock()

    # ---- sizing ----

    def blocks_for(self, num_tokens: int) -> int:
        return max(1, -(-int(num_tokens) // self.block_size))

    def can_allocate(self, num_tokens: int) -> bool:
        with self._lock:
            return len(self._free) >= self.blocks_for(num_tokens)

    # ---- allocation ----

    def allocate(self, seq_id: str, num_tokens: int) -> List[int]:
        """Reserve every page a sequence will ever need (prompt +
        generation budget) up front — exact admission, no mid-decode
        OOM."""
        need = self.blocks_for(num_tokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already allocated")
            if len(self._free) < need:
                raise OutOfKVBlocksError(
                    f"need {need} KV blocks, {len(self._free)} free "
                    f"(pool {self.num_blocks - 1})")
            pages = [self._free.pop() for _ in range(need)]
            self._tables[seq_id] = pages
            return list(pages)

    def free(self, seq_id: str) -> int:
        """Return a finished sequence's pages; freed capacity is
        admittable on the very next engine step."""
        with self._lock:
            pages = self._tables.pop(seq_id, None)
            if not pages:
                return 0
            self._free.extend(reversed(pages))
            return len(pages)

    def block_table(self, seq_id: str) -> Optional[List[int]]:
        with self._lock:
            t = self._tables.get(seq_id)
            return list(t) if t else None

    # ---- telemetry (autoscaler signal: docs/LLM_SERVING.md) ----

    def occupancy(self) -> float:
        """Fraction of the usable pool currently owned by sequences."""
        with self._lock:
            usable = self.num_blocks - 1
            return (usable - len(self._free)) / max(1, usable)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            usable = self.num_blocks - 1
            used = usable - len(self._free)
            return {"kv_blocks_total": usable,
                    "kv_blocks_used": used,
                    "kv_block_size": self.block_size,
                    "kv_occupancy": used / max(1, usable),
                    "kv_sequences": len(self._tables)}
