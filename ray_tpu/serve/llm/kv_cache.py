"""Paged (block) KV cache: the allocator behind continuous batching.

Reference analogue: vLLM's BlockSpaceManager (PAPERS.md "Fine-Tuning
and Serving Gemma 4 31B on Google Cloud TPU" serves through the same
design). The cache is a fixed pool of fixed-size pages; each sequence
owns a *block table* mapping its logical token positions to physical
pages. Growing a sequence by one token allocates at most one page;
finishing a sequence returns all its pages to the free list instantly.
Admission control is therefore exact: a prompt of L tokens with a
budget of G generated tokens needs ``ceil((L + G) / block_size)``
pages, and the engine refuses to admit what it cannot finish —
sequences never deadlock mid-decode waiting for pages.

Page 0 is reserved as the *null page*: batch-padding rows point every
block-table entry at it, so padded jit steps scatter their garbage
into scratch instead of a live sequence's memory.

Pages are *refcounted* so the radix prefix cache (prefix_cache.py) can
share read-only prompt pages across sequences: ``allocate_with_prefix``
maps a cached prefix into a new sequence's block table by bumping the
shared pages' refcounts, ``copy_on_write`` gives a sequence a private
copy of a shared page before it writes into it, and a page returns to
the free list only when its last reference (sequence table or cache
branch) drops.

The pool itself is storage-agnostic (``make_pages`` builds numpy or
jax arrays per layer on demand) — the allocator tracks only indices,
so the same bookkeeping serves the numpy toy adapter and the jitted
flax adapters.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple


class OutOfKVBlocksError(Exception):
    """The pool cannot satisfy an allocation — the engine keeps the
    sequence WAITING (or sheds it) rather than admitting work it
    cannot finish."""


class PagedKVCache:
    """Block allocator + occupancy accounting for one replica's pool.

    Thread-safe: the engine thread allocates/frees while actor threads
    read occupancy for admission and telemetry.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (page 0 is reserved)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # page 0 reserved as the null/scratch page for padding rows
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._tables: Dict[str, List[int]] = {}   # seq id -> pages
        self._refs: Dict[int, int] = {}           # page -> reference count
        self._lock = threading.Lock()

    # ---- sizing ----

    def blocks_for(self, num_tokens: int) -> int:
        return max(1, -(-int(num_tokens) // self.block_size))

    def can_allocate(self, num_tokens: int) -> bool:
        with self._lock:
            return len(self._free) >= self.blocks_for(num_tokens)

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    # ---- allocation ----

    def allocate(self, seq_id: str, num_tokens: int) -> List[int]:
        """Reserve every page a sequence will ever need (prompt +
        generation budget) up front — exact admission, no mid-decode
        OOM."""
        need = self.blocks_for(num_tokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already allocated")
            if len(self._free) < need:
                raise OutOfKVBlocksError(
                    f"need {need} KV blocks, {len(self._free)} free "
                    f"(pool {self.num_blocks - 1})")
            pages = [self._free.pop() for _ in range(need)]
            for p in pages:
                self._refs[p] = 1
            self._tables[seq_id] = pages
            return list(pages)

    def allocate_with_prefix(self, seq_id: str, num_tokens: int,
                             shared_pages: List[int]) -> List[int]:
        """Admit a sequence whose leading pages are already resident:
        the shared (read-only) pages are mapped into the new block
        table by refcount, and only the remainder comes from the free
        list.  The caller must not write into a shared page without
        ``copy_on_write`` first."""
        need = self.blocks_for(num_tokens)
        n_shared = len(shared_pages)
        if n_shared > need:
            raise ValueError(
                f"prefix covers {n_shared} pages but sequence needs {need}")
        fresh_need = need - n_shared
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already allocated")
            for p in shared_pages:
                if self._refs.get(p, 0) <= 0:
                    raise ValueError(f"shared page {p} is not live")
            if len(self._free) < fresh_need:
                raise OutOfKVBlocksError(
                    f"need {fresh_need} fresh KV blocks "
                    f"({n_shared} shared), {len(self._free)} free")
            for p in shared_pages:
                self._refs[p] += 1
            fresh = [self._free.pop() for _ in range(fresh_need)]
            for p in fresh:
                self._refs[p] = 1
            pages = list(shared_pages) + fresh
            self._tables[seq_id] = pages
            return list(pages)

    def incref(self, pages: Iterable[int]) -> None:
        """Take an extra reference on live pages (prefix-cache branch
        adoption)."""
        with self._lock:
            for p in pages:
                if self._refs.get(p, 0) <= 0:
                    raise ValueError(f"page {p} is not live")
                self._refs[p] += 1

    def decref(self, pages: Iterable[int]) -> int:
        """Drop one reference per page; pages whose count hits zero go
        back to the free list.  Returns how many were actually freed."""
        with self._lock:
            return self._decref_locked(pages)

    def _decref_locked(self, pages: Iterable[int]) -> int:
        freed = 0
        for p in pages:
            n = self._refs.get(p, 0)
            if n <= 0:
                continue
            if n == 1:
                del self._refs[p]
                self._free.append(p)
                freed += 1
            else:
                self._refs[p] = n - 1
        return freed

    def copy_on_write(self, seq_id: str, index: int) -> Tuple[int, int]:
        """Give ``seq_id`` a private copy of block-table entry ``index``
        before it writes into it.  Returns ``(old_page, new_page)`` —
        equal when the page was already private (nothing to do); the
        caller copies the page *contents* old→new when they differ."""
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None or index >= len(table):
                raise ValueError(f"no block {index} for {seq_id!r}")
            old = table[index]
            if self._refs.get(old, 0) <= 1:
                return (old, old)
            if not self._free:
                raise OutOfKVBlocksError(
                    "copy-on-write needs 1 free KV block, 0 free")
            new = self._free.pop()
            self._refs[new] = 1
            self._refs[old] -= 1
            table[index] = new
            return (old, new)

    def ref_count(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    def free(self, seq_id: str) -> int:
        """Drop a finished sequence's references; pages still shared
        with the prefix cache or other sequences stay resident, the
        rest are admittable on the very next engine step."""
        with self._lock:
            pages = self._tables.pop(seq_id, None)
            if not pages:
                return 0
            return self._decref_locked(pages)

    def block_table(self, seq_id: str) -> Optional[List[int]]:
        with self._lock:
            t = self._tables.get(seq_id)
            return list(t) if t else None

    # ---- telemetry (autoscaler signal: docs/LLM_SERVING.md) ----

    def occupancy(self) -> float:
        """Fraction of the usable pool currently owned by sequences."""
        with self._lock:
            usable = self.num_blocks - 1
            return (usable - len(self._free)) / max(1, usable)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            usable = self.num_blocks - 1
            used = usable - len(self._free)
            return {"kv_blocks_total": usable,
                    "kv_blocks_used": used,
                    "kv_block_size": self.block_size,
                    "kv_occupancy": used / max(1, usable),
                    "kv_sequences": len(self._tables)}
