"""Radix prefix KV cache: share prompt pages across requests.

Chat fleets serve many tenants whose requests open with the same
system prompt; recomputing that prefix's KV for every request burns
prefill FLOPs and steals decode steps.  This module keeps a *radix
tree over full KV pages*: each node is one page of ``block_size``
tokens keyed by the exact token chunk it holds.  Admission walks the
tree with the new prompt's tokens — every matched node is a page of
KV the new sequence can map read-only into its block table
(``PagedKVCache.allocate_with_prefix``) and skip at prefill time.

Sharing is safe because pages are refcounted and strictly read-only
once published: a sequence that must write *into* a shared page (the
common whole-prompt-cached case, where the last prompt token is
recomputed to produce first-token logits) takes a private copy first
(copy-on-extend via ``PagedKVCache.copy_on_write``).

The cache holds its own reference on every published page, so a page
stays resident after its donor sequence finishes.  Under pool
pressure the engine calls ``evict`` which drops least-recently-used
*unreferenced* leaf branches (pages no live sequence maps) until
enough pages are free — hot shared prefixes survive, cold one-off
prompts are recycled first.

Tree operations are O(prompt_len / block_size) dict hops; the tree is
tiny next to the pages it indexes.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from .kv_cache import PagedKVCache


class _Node:
    __slots__ = ("key", "page", "children", "parent", "last_use")

    def __init__(self, key, page, parent):
        self.key = key                     # tuple of block_size tokens
        self.page = page                   # physical page index
        self.children: Dict[tuple, "_Node"] = {}
        self.parent: Optional["_Node"] = parent
        self.last_use = 0


class RadixPrefixCache:
    """Token-prefix radix tree over ``PagedKVCache`` pages."""

    def __init__(self, cache: PagedKVCache):
        self.cache = cache
        self.block_size = cache.block_size
        self._root = _Node(None, -1, None)
        self._clock = 0                    # monotonic LRU counter
        self._nodes = 0
        self._lock = threading.Lock()
        # telemetry (surfaced through engine.metrics())
        self._lookups = 0
        self._hits = 0
        self._hit_tokens = 0
        self._inserted_pages = 0
        self._evicted_pages = 0

    # ---- admission-time lookup ----

    def lookup(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached prefix of ``tokens`` in whole pages.

        Returns ``(matched_tokens, pages)`` and bumps the matched
        path's LRU clock.  Only full pages match — a partial final
        chunk is never shared because its page would still be written.
        """
        bs = self.block_size
        with self._lock:
            self._lookups += 1
            node = self._root
            pages: List[int] = []
            self._clock += 1
            i = 0
            while i + bs <= len(tokens):
                key = tuple(tokens[i:i + bs])
                child = node.children.get(key)
                if child is None:
                    break
                child.last_use = self._clock
                pages.append(child.page)
                node = child
                i += bs
            if pages:
                self._hits += 1
                self._hit_tokens += i
            return i, pages

    # ---- publication ----

    def insert(self, tokens: Sequence[int], block_table: List[int]) -> int:
        """Donate a finished prefill's full prompt pages to the tree.

        ``block_table[i]`` must hold tokens ``[i*bs, (i+1)*bs)``.  Only
        pages completely covered by ``tokens`` are published; chunks
        already present are skipped (first writer wins — both copies
        hold identical KV, the duplicate page simply stays private to
        its sequence).  Returns the number of newly published pages.
        """
        bs = self.block_size
        added = 0
        with self._lock:
            self._clock += 1
            node = self._root
            for i in range(len(tokens) // bs):
                key = tuple(tokens[i * bs:(i + 1) * bs])
                child = node.children.get(key)
                if child is None:
                    page = block_table[i]
                    try:
                        # the cache's own reference keeps the page
                        # resident after the donor sequence finishes
                        self.cache.incref([page])
                    except ValueError:
                        break
                    child = _Node(key, page, node)
                    node.children[key] = child
                    self._nodes += 1
                    self._inserted_pages += 1
                    added += 1
                child.last_use = self._clock
                node = child
        return added

    # ---- eviction ----

    def evict(self, pages_needed: int,
              pinned: Optional[set] = None) -> int:
        """Drop LRU unreferenced leaf branches until ``pages_needed``
        pages are free in the pool (or nothing evictable remains).

        A node is evictable when it is a leaf and no live sequence
        maps its page (cache holds the only reference).  ``pinned``
        pages are never evicted — the engine pins a just-matched
        prefix between lookup and allocation.
        """
        pinned = pinned or set()
        freed_total = 0
        with self._lock:
            while self.cache.free_blocks() < pages_needed:
                victim = None
                for node in self._iter_leaves(self._root):
                    if node.page in pinned:
                        continue
                    if self.cache.ref_count(node.page) != 1:
                        continue   # a live sequence still maps it
                    if victim is None or node.last_use < victim.last_use:
                        victim = node
                if victim is None:
                    break
                victim.parent.children.pop(victim.key, None)
                self._nodes -= 1
                freed_total += self.cache.decref([victim.page])
                self._evicted_pages += 1
        return freed_total

    def _iter_leaves(self, node):
        for child in node.children.values():
            if child.children:
                yield from self._iter_leaves(child)
            else:
                yield child

    # ---- telemetry ----

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "prefix_nodes": self._nodes,
                "prefix_lookups": self._lookups,
                "prefix_hits": self._hits,
                "prefix_hit_tokens_total": self._hit_tokens,
                "prefix_inserted_pages": self._inserted_pages,
                "prefix_evicted_pages": self._evicted_pages,
            }

    def __len__(self) -> int:
        with self._lock:
            return self._nodes
