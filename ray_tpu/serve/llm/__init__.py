"""ray_tpu.serve.llm — LLM inference on the serve data plane.

Continuous batching + paged KV cache + end-to-end token streaming
(docs/LLM_SERVING.md). The pieces:

  engine.LLMEngine         per-replica continuous-batching scheduler
  kv_cache.PagedKVCache    refcounted block allocator (vLLM-style pages)
  prefix_cache.RadixPrefixCache  shared-prompt radix KV cache
  spec_decode              draft models + greedy speculative verify
  disagg.KVShipper         prefill→decode KV-page handoff (plasmax)
  model_runner             ToyAdapter / FlaxModelAdapter (gpt2, llama)
  deployment.LLMServer     the serve deployment callable

Typical use::

    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMServer

    dep = serve.deployment(name="llm", num_replicas=2)(LLMServer)
    handle = serve.run(dep.bind("gpt2"), route_prefix="/llm")
    for chunk in handle.stream({"prompt": "hello", "max_new_tokens": 32}):
        print(chunk["text"], end="", flush=True)

HTTP: POST the same payload with ``"stream": true`` (or
``Accept: text/event-stream``) for SSE token streaming.
"""

from ray_tpu.serve.llm.deployment import ByteTokenizer, LLMServer
from ray_tpu.serve.llm.disagg import KVShipError, KVShipper
from ray_tpu.serve.llm.engine import (EngineConfig, LLMEngine,
                                      SamplingParams)
from ray_tpu.serve.llm.kv_cache import OutOfKVBlocksError, PagedKVCache
from ray_tpu.serve.llm.model_runner import (FlaxModelAdapter, ToyAdapter,
                                            make_adapter)
from ray_tpu.serve.llm.prefix_cache import RadixPrefixCache
from ray_tpu.serve.llm.spec_decode import (FlaxDraft, ToyDraft,
                                           greedy_verify, make_draft)

__all__ = [
    "LLMServer", "LLMEngine", "EngineConfig", "SamplingParams",
    "PagedKVCache", "OutOfKVBlocksError", "ToyAdapter",
    "FlaxModelAdapter", "make_adapter", "ByteTokenizer",
    "RadixPrefixCache", "KVShipper", "KVShipError",
    "ToyDraft", "FlaxDraft", "greedy_verify", "make_draft",
]
