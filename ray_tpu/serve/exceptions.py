"""Serve-specific exceptions.

Reference analogue: serve/exceptions.py (BackPressureError). These must
be picklable with their args intact — they travel through the object
plane as the ``cause`` of an ``ActorError`` and are type-checked on the
caller side (router retry, proxy 503 mapping).
"""

from __future__ import annotations


class ReplicaOverloadedError(Exception):
    """A replica's bounded ingress queue was full and the request was
    shed. Retriable: the caller should try another replica (the Router
    and HTTP proxy do this automatically; the proxy maps exhaustion to
    HTTP 503)."""

    def __init__(self, deployment_name: str = "", queue_len: int = 0,
                 limit: int = 0):
        self.deployment_name = deployment_name
        self.queue_len = queue_len
        self.limit = limit
        super().__init__(
            f"replica of deployment {deployment_name!r} overloaded: "
            f"{queue_len} requests in flight >= limit {limit} "
            f"(max_concurrent_queries + max_queued_requests); retriable")

    def __reduce__(self):
        return (ReplicaOverloadedError,
                (self.deployment_name, self.queue_len, self.limit))


class BatchSubmitTimeoutError(TimeoutError):
    """A @serve.batch submit waited longer than ``submit_timeout_s`` for
    the batch fn to produce a result (wedged or very slow batch fn)."""


class StreamBrokenError(Exception):
    """A token stream's replica died (or its stream state was lost)
    mid-generation. The partial output is attached so the caller can
    decide to retry the whole request or surface a CLEAN failure —
    never a silent truncation (docs/LLM_SERVING.md).
    """

    def __init__(self, deployment_name: str = "",
                 tokens_so_far: int = 0, cause: str = ""):
        self.deployment_name = deployment_name
        self.tokens_so_far = tokens_so_far
        self.cause = cause
        super().__init__(
            f"token stream of deployment {deployment_name!r} broke "
            f"after {tokens_so_far} tokens: {cause or 'replica died'}")

    def __reduce__(self):
        return (StreamBrokenError,
                (self.deployment_name, self.tokens_so_far, self.cause))
