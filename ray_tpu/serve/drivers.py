"""Driver deployments: HTTP ingress multiplexing over a deployment graph.

Reference analogue: serve/drivers.py (DAGDriver:41) — one ingress
deployment that owns a {route: sub-graph} table and dispatches requests
by path, so a single serve.run deploys a whole multi-endpoint app.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.api import deployment


class _DAGDriverImpl:
    """Dispatches on the request path to bound sub-deployments.

    ``routes`` values are DeploymentHandles by the time the replica
    constructs (serve.run converts bound deployments inside dict args).
    """

    def __init__(self, routes: Dict[str, Any], http_adapter=None):
        self.routes = {("/" + k.strip("/")) if k != "/" else "/": v
                       for k, v in routes.items()}
        # payload transform applied before dispatch (reference:
        # DAGDriver's http_adapter; see serve/http_adapters.py)
        self.http_adapter = http_adapter

    def _match(self, path: str) -> Optional[str]:
        path = "/" + path.strip("/") if path != "/" else "/"
        best, best_len = None, -1
        for prefix in self.routes:
            if (path == prefix or prefix == "/"
                    or path.startswith(prefix + "/")):
                if len(prefix) > best_len:
                    best, best_len = prefix, len(prefix)
        return best

    def __call__(self, payload: Any = None, __serve_path__: str = "/"):
        prefix = self._match(__serve_path__)
        if prefix is None:
            raise KeyError(f"no DAG route matches {__serve_path__!r}")
        if self.http_adapter is not None and payload is not None:
            payload = self.http_adapter(payload)
        handle = self.routes[prefix]
        ref = (handle.remote(payload) if payload is not None
               else handle.remote())
        return ray_tpu.get(ref, timeout=60.0)

    def get_routes(self) -> Dict[str, str]:
        return {k: repr(v) for k, v in self.routes.items()}


DAGDriver = deployment(_DAGDriverImpl, name="DAGDriver",
                       pass_http_path=True)
