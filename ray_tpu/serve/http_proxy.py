"""HTTP ingress — proxy actor routing HTTP requests to replicas.

Reference analogue: serve/_private/http_proxy.py:387 (HTTPProxyActor,
HTTPProxy.__call__:312 over uvicorn/ASGI). Here: a stdlib
ThreadingHTTPServer inside an actor; each request thread routes through
the shared backpressure-aware Router (load-aware selection + overload
retry on other replicas), so HTTP and handle traffic obey the same
``max_concurrent_queries`` flow control. A saturated deployment sheds:
when every replica is at capacity (router assign times out after
``RTPU_SERVE_PROXY_ASSIGN_TIMEOUT_S``) or the overload retries exhaust,
the proxy answers a retriable 503 instead of queueing unboundedly.

Controller HA: the proxy serves every request from its CACHED route
table + replica sets — a controller outage never stops the data plane.
Synchronous refreshes degrade to the cache on failure, and the route
long-poll reconnects with backoff once the restarted controller is
back (see serve/_private/long_poll.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse


class HTTPProxyActor:
    def __init__(self, controller_name: str, host: str = "127.0.0.1",
                 port: int = 8000):
        import ray_tpu
        from ray_tpu.serve._private.long_poll import LongPollClient
        from ray_tpu.serve._private.router import Router
        self._controller = ray_tpu.get_actor(controller_name)
        self._router = Router(self._controller)
        self._routes: Dict[str, str] = {}   # route_prefix -> deployment
        # routes update via long-poll, NOT a controller RPC per request
        self._route_poller = LongPollClient(
            self._controller, "route_table", self._on_route_update)
        self.host, self.port = host, port
        self._server: Optional[ThreadingHTTPServer] = None
        self._refresh_routes()
        self._start_server()

    def _on_route_update(self, table):
        self._pass_path = {name: bool(info.get("pass_http_path"))
                           for name, info in (table or {}).items()}
        self._pass_method = {name: bool(info.get("pass_http_method"))
                             for name, info in (table or {}).items()}
        routes = {}
        for name, info in (table or {}).items():
            prefix = info.get("route_prefix")
            if prefix:
                routes[prefix.rstrip("/") or "/"] = name
        self._routes = routes

    def _refresh_routes(self):
        """Synchronous route pull, resilient to a controller outage:
        the proxy MUST keep answering from its cached routes while the
        controller restarts (the long-poll re-delivers on recovery)."""
        import ray_tpu
        try:
            _, table = ray_tpu.get(
                self._controller.get_route_table.remote(), timeout=10.0)
        except Exception:
            return  # keep serving the cached table
        if table is not None:
            self._on_route_update(table)

    def _match(self, path: str):
        """Longest-prefix route match → (deployment name, matched prefix)
        or None."""
        path = path.rstrip("/") or "/"
        best, best_len, best_prefix = None, -1, "/"
        for prefix, name in self._routes.items():
            if (path == prefix or path.startswith(
                    prefix if prefix.endswith("/") else prefix + "/")
                    or prefix == "/"):
                if len(prefix) > best_len:
                    best, best_len, best_prefix = name, len(prefix), prefix
        if best is None:
            return None
        return best, best_prefix

    def _start_server(self):
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _handle(self, body: Optional[bytes]):
                import ray_tpu
                # client-supplied request id: propagated proxy → router
                # → replica (reserved __rtpu_request_id__ kwarg) and
                # echoed on EVERY response, so a client can join its
                # observation with the replica's request ledger
                self._request_id = self.headers.get("X-Request-Id")
                parsed = urlparse(self.path)
                matched = proxy._match(parsed.path)
                if matched is None:
                    # maybe deployed after our last long-poll tick
                    proxy._refresh_routes()
                    matched = proxy._match(parsed.path)
                if matched is None:
                    self._respond(404, {"error":
                                        f"no route for {parsed.path}"})
                    return
                name, route_prefix = matched
                if body is not None and body:
                    try:
                        payload = json.loads(body)
                    except Exception:
                        payload = body.decode("utf-8", "replace")
                else:
                    q = parse_qs(parsed.query)
                    payload = {k: v[0] if len(v) == 1 else v
                               for k, v in q.items()} if q else None
                from ray_tpu import exceptions as rexc
                from ray_tpu.serve._private.router import \
                    is_overload_error
                last_err: Optional[Exception] = None
                # only idempotent requests are retried — a POST may have
                # run side effects on the replica before it died
                attempts = 4 if self.command == "GET" else 1
                try:
                    assign_timeout = float(os.environ.get(
                        "RTPU_SERVE_PROXY_ASSIGN_TIMEOUT_S", 5.0))
                except ValueError:
                    assign_timeout = 5.0
                # proxy root span: everything below (route match,
                # router assign, replica, retries) nests under it, so
                # `ray-tpu trace critical-path` attributes the full
                # proxy-observed latency; trace id = X-Request-Id when
                # the client sent one (echoed as X-Trace-Id either way)
                from ray_tpu._private import tracing
                proxy_span = None
                if tracing.enabled():
                    proxy_span = tracing.Span(
                        self._request_id or tracing.new_trace_id(),
                        f"serve.proxy:{name}", kind="serve.proxy",
                        phase="transfer",
                        attrs={"path": parsed.path,
                               "method": self.command,
                               "request_id": self._request_id})
                    self._proxy_span = proxy_span  # closed in _respond
                # token streaming (serve/llm): a JSON body with
                # "stream": true — or an Accept: text/event-stream
                # header — switches this request to SSE; tokens are
                # written the moment the engine decodes them
                wants_sse = ("text/event-stream"
                             in (self.headers.get("Accept") or ""))
                if (isinstance(payload, dict) and payload.get("stream")) \
                        or (wants_sse and isinstance(payload,
                                                     (dict, type(None)))):
                    self._handle_stream(name, payload or {})
                    return
                for attempt in range(attempts):
                    try:
                        kwargs = {}
                        if getattr(proxy, "_pass_path", {}).get(name):
                            # driver deployments (DAGDriver) multiplex on
                            # the request path BELOW their route prefix
                            sub = parsed.path
                            if route_prefix != "/" and \
                                    sub.startswith(route_prefix):
                                sub = sub[len(route_prefix):] or "/"
                            kwargs["__serve_path__"] = sub
                            if getattr(proxy, "_pass_method",
                                       {}).get(name):
                                kwargs["__serve_method__"] = self.command
                        result = proxy._router.execute_request(
                            name, "__call__",
                            (payload,) if payload is not None else (),
                            kwargs, get_timeout=60.0,
                            assign_timeout=assign_timeout,
                            request_id=self._request_id,
                            trace_parent=(proxy_span.child_ctx()
                                          if proxy_span else None))
                        if isinstance(result, dict) and \
                                "__serve_http_status__" in result:
                            # structured routing miss from an ingress
                            # deployment (serve/ingress.py)
                            self._respond(
                                result["__serve_http_status__"],
                                {"error": result.get("error")})
                            return
                        self._respond(200, result)
                        return
                    except rexc.GetTimeoutError as e:
                        # the replica accepted the request but didn't
                        # answer in time — not an overload signal
                        self._respond(504, {"error": repr(e)})
                        return
                    except TimeoutError as e:
                        # router assign timed out: every replica is at
                        # max_concurrent_queries — shed with a
                        # retriable 503 instead of queueing unboundedly
                        self._respond(503, {
                            "error": f"deployment {name!r} saturated: "
                                     f"{e}",
                            "retryable": True})
                        return
                    except (rexc.ActorDiedError,
                            rexc.ActorUnavailableError) as e:
                        # routed to a replica that died (e.g. torn down by
                        # a redeploy the long-poll hasn't delivered yet):
                        # resync membership and retry
                        last_err = e
                        time.sleep(0.3 * (attempt + 1))
                        proxy._router.force_refresh()
                        proxy._refresh_routes()
                        fresh = proxy._match(parsed.path)
                        if fresh is None:
                            break
                        name, route_prefix = fresh
                    except Exception as e:
                        if is_overload_error(e):
                            # every retry landed on a full replica —
                            # bounded queues shed, the client retries
                            self._respond(503, {
                                "error": f"deployment {name!r} "
                                         f"overloaded: {e}".split(
                                             "\n")[0],
                                "retryable": True})
                            return
                        self._respond(500, {"error": repr(e)})
                        return
                if attempts == 1:
                    # non-idempotent request, NOT retried here and must
                    # not be advertised retryable — the replica may have
                    # run side effects before dying. Resync for the next
                    # request.
                    proxy._router.force_refresh()
                    proxy._refresh_routes()
                    self._respond(500, {"error": repr(last_err)})
                else:
                    # idempotent and safe to retry later (a redeploy was
                    # likely still settling)
                    self._respond(503, {"error": repr(last_err),
                                        "retryable": True})

            def _handle_stream(self, name: str, payload: dict):
                """SSE token streaming: open a stream through the
                shared router (same admission/overload behavior as
                unary), then write one ``data:`` event per token chunk
                as it lands — the client reads the first token while
                the tail is still decoding. A mid-stream replica death
                surfaces as an explicit error event, never a silently
                truncated 200."""
                import json as _json

                from ray_tpu.serve.exceptions import StreamBrokenError
                try:
                    assign_timeout = float(os.environ.get(
                        "RTPU_SERVE_PROXY_ASSIGN_TIMEOUT_S", 5.0))
                except ValueError:
                    assign_timeout = 5.0
                sp = getattr(self, "_proxy_span", None)
                try:
                    stream = proxy._router.open_stream(
                        name, payload, request_id=self._request_id,
                        assign_timeout=assign_timeout,
                        trace_parent=(sp.child_ctx() if sp else None))
                except TimeoutError as e:
                    self._respond(503, {"error": f"deployment {name!r} "
                                                 f"saturated: {e}",
                                        "retryable": True})
                    return
                except Exception as e:
                    if is_overload_error(e):
                        self._respond(503, {
                            "error": str(e).split("\n")[0],
                            "retryable": True})
                    else:
                        self._respond(500, {"error": repr(e)})
                    return

                def fold_usage(chunk):
                    return {k: v for k, v in chunk.items()
                            if k in ("tokens", "text", "cursor", "done",
                                     "n_tokens", "finish_reason",
                                     "ttft_s")}

                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                # no Content-Length: the body ends when the connection
                # closes (HTTP/1.1 §3.3.3) — stdlib-client friendly
                self.send_header("Connection", "close")
                if self._request_id:
                    self.send_header("X-Request-Id", self._request_id)
                if sp is not None:
                    self.send_header("X-Trace-Id", sp.trace_id)
                self.end_headers()
                status = "ok"
                try:
                    for chunk in stream:
                        self.wfile.write(
                            b"data: "
                            + _json.dumps(fold_usage(chunk)).encode()
                            + b"\n\n")
                        self.wfile.flush()
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                except StreamBrokenError as e:
                    status = "error"
                    try:
                        self.wfile.write(
                            b"data: " + _json.dumps(
                                {"error": str(e), "done": True,
                                 "tokens_so_far": e.tokens_so_far}
                            ).encode() + b"\n\n")
                        self.wfile.flush()
                    except OSError:
                        pass
                except OSError:
                    # client went away: abandon generation server-side
                    status = "error"
                    stream.cancel()
                finally:
                    self.close_connection = True
                    if sp is not None:
                        self._proxy_span = None
                        sp.finish(status)

            def _respond(self, code: int, result: Any):
                sp = getattr(self, "_proxy_span", None)
                if sp is not None:
                    self._proxy_span = None
                    sp.finish("ok" if code < 400
                              else "shed" if code == 503 else "error")
                try:
                    data = json.dumps(result).encode()
                    ctype = "application/json"
                except (TypeError, ValueError):
                    data = str(result).encode()
                    ctype = "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                if getattr(self, "_request_id", None):
                    self.send_header("X-Request-Id", self._request_id)
                if sp is not None:
                    # the join key for `ray-tpu trace show` even when the
                    # client sent no X-Request-Id
                    self.send_header("X-Trace-Id", sp.trace_id)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._handle(None)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self._handle(self.rfile.read(n) if n else b"")

        for attempt in range(32):
            try:
                self._server = ThreadingHTTPServer(
                    (self.host, self.port + attempt), Handler)
                self.port = self.port + attempt
                break
            except OSError:
                continue
        if self._server is None:
            raise RuntimeError("no free port for HTTP proxy")
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

    def sync_routes(self) -> bool:
        """Synchronously pull the current route table + replica sets —
        the deploy barrier serve.run uses so a request right after it
        returns cannot see pre-deploy routing."""
        self._refresh_routes()
        self._router.force_refresh()
        return True

    def get_port(self) -> int:
        return self.port

    def ping(self):
        return "pong"

    def shutdown(self):
        if self._server:
            self._server.shutdown()
        self._route_poller.stop()
        self._router.stop()
        return "ok"
