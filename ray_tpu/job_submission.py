"""Job submission: SDK client + supervisor actors.

Reference analogue: dashboard/modules/job/ (JobManager job_manager.py:431,
submit_job:691, per-job JobSupervisor:133 running the entrypoint as a
subprocess and streaming logs; REST in job_head.py, sdk.py, cli.py).
The JobSubmissionClient here talks either directly to the cluster
(``ray_tpu://`` — in-process) or to the dashboard REST endpoint
(``http://host:port``).
"""

from __future__ import annotations

import json
import os
import subprocess
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

_JOB_KV_PREFIX = "@job/"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class JobSupervisor:
    """Actor running one job's entrypoint as a subprocess
    (reference: job_manager.py:133)."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[Dict[str, Any]] = None,
                 metadata: Optional[Dict[str, str]] = None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.log_path = os.path.join(tempfile.gettempdir(),
                                     f"rtpu-job-{job_id}.log")
        self.proc: Optional[subprocess.Popen] = None
        self._put_info({
            "job_id": job_id, "entrypoint": entrypoint,
            "status": JobStatus.PENDING, "metadata": metadata or {},
            "start_time": time.time(), "log_path": self.log_path,
        })
        env = dict(os.environ)
        env["RTPU_ADDRESS"] = ray_tpu._worker_mod.global_worker(
            ).gcs_address
        env["RTPU_JOB_ID"] = job_id
        for k, v in (runtime_env or {}).get("env_vars", {}).items():
            env[k] = str(v)
        cwd = (runtime_env or {}).get("working_dir") or None
        logf = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            ["/bin/sh", "-c", entrypoint], stdout=logf, stderr=logf,
            env=env, cwd=cwd)
        self._update(status=JobStatus.RUNNING)

    def _put_info(self, info: Dict[str, Any]):
        w = ray_tpu._worker_mod.global_worker()
        w.call_sync(w.gcs, "kv_put",
                    {"key": _JOB_KV_PREFIX + self.job_id,
                     "value": json.dumps(info).encode(),
                     "overwrite": True}, timeout=30)

    def _get_info(self) -> Dict[str, Any]:
        w = ray_tpu._worker_mod.global_worker()
        r = w.call_sync(w.gcs, "kv_get",
                        {"key": _JOB_KV_PREFIX + self.job_id},
                        timeout=30)
        v = r.get("value")
        return json.loads(v) if v else {}

    def _update(self, **fields):
        info = self._get_info()
        info.update(fields)
        self._put_info(info)

    def poll(self) -> str:
        """Refresh and return the job status."""
        if self.proc is None:
            return JobStatus.PENDING
        rc = self.proc.poll()
        if rc is None:
            return JobStatus.RUNNING
        info = self._get_info()
        if info.get("status") in (JobStatus.RUNNING, JobStatus.PENDING):
            status = (JobStatus.SUCCEEDED if rc == 0
                      else JobStatus.FAILED)
            self._update(status=status, end_time=time.time(),
                         return_code=rc)
            return status
        return info.get("status", JobStatus.FAILED)

    def stop(self) -> str:
        # already-terminal jobs keep their status; stop only acts on a
        # live process
        current = self.poll()
        if current not in (JobStatus.RUNNING, JobStatus.PENDING):
            return current
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self._update(status=JobStatus.STOPPED, end_time=time.time())
        return JobStatus.STOPPED

    def get_logs(self) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""


def _kv(method: str, payload: dict) -> dict:
    w = ray_tpu._worker_mod.global_worker()
    return w.call_sync(w.gcs, method, payload, timeout=30)


class JobSubmissionClient:
    """SDK entry point (reference: dashboard/modules/job/sdk.py).

    address=None / "ray_tpu://..." → drive jobs in-cluster via actors;
    "http://host:port" → drive the dashboard REST API.
    """

    def __init__(self, address: Optional[str] = None):
        self._http = None
        if address and address.startswith("http"):
            self._http = address.rstrip("/")
        elif not ray_tpu.is_initialized():
            ray_tpu.init(address=address.replace("ray_tpu://", "")
                         if address else None)

    # ---- REST transport ----

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        import urllib.request
        req = urllib.request.Request(
            self._http + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"},
            method=method)
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read() or b"{}")

    # ---- API ----

    def submit_job(self, *, entrypoint: str,
                   job_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        job_id = job_id or f"rtpu-job-{uuid.uuid4().hex[:8]}"
        if self._http:
            r = self._request("POST", "/api/jobs/", {
                "entrypoint": entrypoint, "job_id": job_id,
                "runtime_env": runtime_env, "metadata": metadata})
            return r["job_id"]
        sup_cls = ray_tpu.remote(
            name=f"JOB_SUPERVISOR::{job_id}", lifetime="detached",
            max_concurrency=4)(JobSupervisor)
        sup = sup_cls.remote(job_id, entrypoint, runtime_env, metadata)
        # block until the supervisor has recorded the job and spawned the
        # entrypoint, so an immediate status/info query can't miss it
        ray_tpu.get(sup.poll.remote(), timeout=60.0)
        return job_id

    def _supervisor(self, job_id: str):
        return ray_tpu.get_actor(f"JOB_SUPERVISOR::{job_id}")

    def get_job_status(self, job_id: str) -> str:
        if self._http:
            return self._request("GET", f"/api/jobs/{job_id}")["status"]
        try:
            return ray_tpu.get(self._supervisor(job_id).poll.remote(),
                               timeout=30)
        except Exception:
            info = self.get_job_info(job_id)
            return info.get("status", JobStatus.FAILED)

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        if self._http:
            return self._request("GET", f"/api/jobs/{job_id}")
        r = _kv("kv_get", {"key": _JOB_KV_PREFIX + job_id})
        v = r.get("value")
        if v is None:
            raise ValueError(f"unknown job {job_id!r}")
        return json.loads(v)

    def get_job_logs(self, job_id: str) -> str:
        if self._http:
            return self._request("GET",
                                 f"/api/jobs/{job_id}/logs")["logs"]
        try:
            return ray_tpu.get(
                self._supervisor(job_id).get_logs.remote(), timeout=30)
        except Exception:
            info = self.get_job_info(job_id)
            try:
                with open(info["log_path"], errors="replace") as f:
                    return f.read()
            except Exception:
                return ""

    def stop_job(self, job_id: str) -> bool:
        if self._http:
            return self._request("POST",
                                 f"/api/jobs/{job_id}/stop")["stopped"]
        ray_tpu.get(self._supervisor(job_id).stop.remote(), timeout=30)
        return True

    def list_jobs(self) -> List[Dict[str, Any]]:
        if self._http:
            return self._request("GET", "/api/jobs/")["jobs"]
        keys = _kv("kv_keys", {"prefix": _JOB_KV_PREFIX}).get("keys", [])
        out = []
        for k in keys:
            v = _kv("kv_get", {"key": k}).get("value")
            if v:
                out.append(json.loads(v))
        return out

    def wait_until_finish(self, job_id: str, timeout: float = 300.0
                          ) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            s = self.get_job_status(job_id)
            if s in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                     JobStatus.STOPPED):
                return s
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
