"""In-process multi-node test cluster.

Reference analogue: python/ray/cluster_utils.py (Cluster:99 / add_node:165) —
multiple raylets run as separate processes on one machine sharing one GCS;
this is how multi-node behavior (spillback, PGs, object transfer, node death)
is tested without a real cluster (SURVEY.md §4). On a TPU host, chips are
partitioned between simulated nodes via the TPU resource quantity.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import node as node_mod
from ray_tpu.common.config import SystemConfig


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict[str, Any]] = None,
                 connect: bool = False,
                 _system_config: Optional[Dict[str, Any]] = None):
        self.config = SystemConfig().apply_env_overrides()
        if _system_config:
            self.config.update(_system_config)
        self.session_dir = node_mod.new_session_dir()
        self.head: Optional[node_mod.NodeProcesses] = None
        self.worker_nodes: List[Dict[str, Any]] = []
        self.gcs_address = ""
        if initialize_head:
            args = head_node_args or {}
            self.head = node_mod.start_head(
                self.config,
                resources=self._res(args),
                labels=args.get("labels"),
                object_store_memory=args.get("object_store_memory"),
                session_dir=self.session_dir)
            self.gcs_address = self.head.gcs_address
        if connect:
            self.connect()

    @staticmethod
    def _res(args: Dict[str, Any]) -> Dict[str, float]:
        res = dict(args.get("resources", {}))
        if "num_cpus" in args:
            res["CPU"] = float(args["num_cpus"])
        if "num_tpus" in args:
            res["TPU"] = float(args["num_tpus"])
        if "num_gpus" in args:
            res["GPU"] = float(args["num_gpus"])
        return res

    @property
    def address(self) -> str:
        return self.gcs_address

    def add_node(self, **args) -> Dict[str, Any]:
        info = node_mod.add_node(
            self.session_dir, self.gcs_address,
            resources=self._res(args),
            labels=args.get("labels"),
            object_store_memory=args.get("object_store_memory"),
            env_overrides=args.get("env_overrides"))
        self.worker_nodes.append(info)
        return info

    def add_nodes(self, count: int, **args) -> List[Dict[str, Any]]:
        """Spawn ``count`` identical raylets concurrently (scale tests:
        the 50-node simulated cluster comes up in one wave instead of
        paying a serial ready-wait per node)."""
        import json as _json
        import os as _os
        from ray_tpu.common.ids import NodeID
        procs = []
        for _ in range(count):
            node_id = NodeID.from_random().hex()
            proc = node_mod.start_raylet(
                self.session_dir, self.gcs_address, node_id,
                self._res(args), args.get("labels") or {}, is_head=False,
                object_store_memory=args.get("object_store_memory"),
                env_overrides=args.get("env_overrides"))
            procs.append((node_id, proc))
        infos = []
        for node_id, proc in procs:
            info = _json.loads(node_mod._wait_file(
                _os.path.join(self.session_dir,
                              f"raylet_{node_id[:8]}.json"),
                timeout=120.0))
            info["proc"] = proc
            info["node_id"] = node_id
            self.worker_nodes.append(info)
            infos.append(info)
        return infos

    def preempt_node(self, info: Dict[str, Any],
                     grace_s: Optional[float] = None) -> Dict[str, Any]:
        """Deliver a preemption notice to a worker node. With a custom
        ``grace_s`` the notice rides the GCS ``preempt_node`` RPC;
        otherwise SIGUSR2 hits the raylet directly (the spot-VM path).
        The node drains (stops taking work, lets in-flight work finish,
        signals trainers to checkpoint) and then exits — it is NOT
        removed from ``worker_nodes`` here; the GCS marks it dead when
        the drain completes."""
        if grace_s is None:
            node_mod.preempt_raylet(info["proc"])
            return {"draining": True}
        from ray_tpu._private import worker as wmod
        w = wmod.global_worker()
        return w.call_sync(w.gcs, "preempt_node", {
            "node_id": info["node_id"], "grace_s": grace_s})

    def remove_node(self, info: Dict[str, Any], allow_graceful: bool = False):
        proc = info["proc"]
        if allow_graceful:
            proc.terminate()
        else:
            proc.kill()
        proc.wait(timeout=10)
        if not allow_graceful and info.get("store_path"):
            # SIGKILLed raylet can't unlink its own shm segment
            try:
                os.unlink(info["store_path"])
            except OSError:
                pass
        if info in self.worker_nodes:
            self.worker_nodes.remove(info)

    def connect(self, namespace: str = ""):
        import ray_tpu
        os.environ["RTPU_SESSION_DIR"] = self.session_dir
        ray_tpu.init(address=self.gcs_address, namespace=namespace)

    def wait_for_nodes(self, timeout: float = 30.0):
        import ray_tpu
        expected = 1 + len(self.worker_nodes) if self.head else \
            len(self.worker_nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) >= expected:
                return
            time.sleep(0.1)
        raise TimeoutError("nodes did not come up")

    def shutdown(self):
        import ray_tpu
        ray_tpu.shutdown()
        for info in self.worker_nodes:
            proc = info["proc"]
            try:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except Exception:
                    proc.kill()
            except Exception:
                pass
            if info.get("store_path"):
                try:
                    os.unlink(info["store_path"])
                except OSError:
                    pass
        if self.head is not None:
            self.head.kill_all()
