"""Usage stats collection (opt-IN, local-only).

Reference analogue: ``python/ray/_private/usage/usage_lib.py`` +
``gcs_client/usage_stats_client.cc`` — the reference collects cluster
metadata (version, python, OS, library usage, node counts) and reports
it opt-OUT. This environment has zero egress, so the redesign is
opt-IN (``RTPU_USAGE_STATS_ENABLED=1``) and writes the report to
``<session_dir>/usage_stats.json`` only; the ``report_url`` seam is
where a deployment would POST it.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import time
from typing import Any, Dict, Optional, Set

_lib_usages: Set[str] = set()


def usage_stats_enabled() -> bool:
    return os.environ.get("RTPU_USAGE_STATS_ENABLED", "") not in ("", "0")


def record_library_usage(name: str):
    """Called by library entry points (tune.run, serve.start, ...);
    cheap set-add, collected into the report (reference:
    usage_lib.record_library_usage)."""
    _lib_usages.add(name)


def _collect(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    try:
        import jax
        jax_version = jax.__version__
    except Exception:
        jax_version = None
    report = {
        "schema_version": 1,
        "collected_at": time.time(),
        "python_version": platform.python_version(),
        "os": sys.platform,
        "machine": platform.machine(),
        "jax_version": jax_version,
        "libraries_used": sorted(_lib_usages),
        "total_success": 0,  # would-be report deliveries (no egress here)
        "total_failed": 0,
        "seq_no": 1,
    }
    report.update(extra or {})
    return report


def write_report(session_dir: str,
                 extra: Optional[Dict[str, Any]] = None
                 ) -> Optional[str]:
    """Write the usage report under the session dir if enabled;
    returns the path (reference: usage_lib.put_cluster_metadata +
    the reporter writing usage_stats.json)."""
    if not usage_stats_enabled():
        return None
    path = os.path.join(session_dir, "usage_stats.json")
    try:
        with open(path, "w") as f:
            json.dump(_collect(extra), f, indent=1)
    except OSError:
        return None
    return path
