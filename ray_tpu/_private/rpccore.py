"""Loader + thin wrapper for the native RPC frame pump (src/rpccore/).

Same ctypes pattern as the plasmax store (object_store.py) and the
dispatch ledger (sched.py): the shared library is built from source on
first use (atomic temp-file rename so racing processes don't corrupt
each other), and EVERY failure mode — missing compiler, build error,
load error, ABI mismatch — degrades to the pure-Python asyncio path in
``_private/protocol.py``.  ``RTPU_NATIVE_RPC=0`` forces the fallback
explicitly; the wire bytes are identical either way
(docs/WIRE_PROTOCOL.md "Implementations").
"""

from __future__ import annotations

import ctypes
import logging
import os
import tempfile
import threading
from typing import List, Optional, Tuple

logger = logging.getLogger(__name__)

_ABI = 4  # must match rpcx_abi_version() in src/rpccore/rpcx.cc

_LIB = None
_LIB_FAILED = False
_LIB_LOCK = threading.Lock()

# event kinds (rpcx.cc)
KIND_FRAME = 1
KIND_CLOSED = 2
KIND_WAKE = 3

_BATCH = 32  # events per rpcx_next_batch call


def env_enabled() -> bool:
    """The RTPU_NATIVE_RPC gate. Default ON: unset/1 means use the
    native pump when it loads; 0/false forces the Python path."""
    return os.environ.get("RTPU_NATIVE_RPC", "1").lower() not in (
        "0", "false", "no")


def available() -> bool:
    """True when the env gate is open AND the library loads."""
    return env_enabled() and _lib() is not None


def _lib():
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _LIB_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        try:
            path = os.path.abspath(os.path.join(
                os.path.dirname(__file__), "..", "core", "librpcx.so"))
            src = os.path.abspath(os.path.join(
                os.path.dirname(path), "..", "..", "src", "rpccore",
                "rpcx.cc"))
            if not os.path.exists(path) or (
                    os.path.exists(src)
                    and os.path.getmtime(src) > os.path.getmtime(path)):
                _build(src, path)
            lib = ctypes.CDLL(path)
            lib.rpcx_abi_version.restype = ctypes.c_int
            if lib.rpcx_abi_version() != _ABI:
                # stale binary from an older source tree (mtime can lie
                # across checkouts): rebuild once, then give up
                _build(src, path)
                lib = ctypes.CDLL(path)
                if lib.rpcx_abi_version() != _ABI:
                    raise RuntimeError(
                        f"librpcx ABI {lib.rpcx_abi_version()} != {_ABI}")
            lib.rpcx_create.restype = ctypes.c_void_p
            lib.rpcx_listen.restype = ctypes.c_int
            lib.rpcx_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rpcx_dial.restype = ctypes.c_long
            lib.rpcx_dial.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.rpcx_listen_tcp.restype = ctypes.c_int
            lib.rpcx_listen_tcp.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
            lib.rpcx_dial_tcp.restype = ctypes.c_long
            lib.rpcx_dial_tcp.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
            lib.rpcx_next_batch.restype = ctypes.c_int
            lib.rpcx_next_batch.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int, ctypes.c_int]
            lib.rpcx_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
            lib.rpcx_send.restype = ctypes.c_int
            lib.rpcx_send.argtypes = [ctypes.c_void_p, ctypes.c_long,
                                      ctypes.c_char_p, ctypes.c_uint32]
            lib.rpcx_close_conn.restype = ctypes.c_int
            lib.rpcx_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.rpcx_wake.argtypes = [ctypes.c_void_p]
            lib.rpcx_shutdown.argtypes = [ctypes.c_void_p]
            lib.rpcx_destroy.argtypes = [ctypes.c_void_p]
            lib.rpcx_stats.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint64)]
            _LIB = lib
        except Exception:
            logger.warning("native RPC pump unavailable; using the "
                           "Python asyncio path", exc_info=True)
            _LIB_FAILED = True
            _LIB = None
    return _LIB


def _build(src: str, out_path: str):
    import subprocess
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(out_path))
    os.close(fd)
    try:
        subprocess.check_call(
            ["g++", "-O2", "-fPIC", "-shared", "-o", tmp, src, "-lpthread"])
        os.replace(tmp, out_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def is_tcp_address(address: str) -> bool:
    """``host:port`` (optionally ``tcp:``-prefixed) vs a unix socket
    path / ``unix:`` address. Mirrors protocol.connect's split."""
    if address.startswith("unix:") or address.startswith("/"):
        return False
    if address.startswith("tcp:"):
        return True
    host, sep, port = address.rpartition(":")
    return bool(sep) and bool(host) and port.isdigit()


def _reset_for_tests():
    """Drop the cached load state so a test can exercise load failure."""
    global _LIB, _LIB_FAILED
    with _LIB_LOCK:
        _LIB = None
        _LIB_FAILED = False


class Pump:
    """One native reactor: a listening socket and/or dialed peers.

    ``next_batch`` runs the reactor in the CALLING thread (GIL released
    inside the C call) — the owner thread is the event loop. ``send``/
    ``close_conn`` are safe from any thread."""

    def __init__(self):
        lib = _lib()
        if lib is None or not env_enabled():
            raise RuntimeError("native RPC pump unavailable")
        self._lib = lib
        self._p = lib.rpcx_create()
        self._destroyed = False
        self._destroy_lock = threading.Lock()
        # reusable out-param arrays (one lane thread drives next_batch)
        self._cids = (ctypes.c_long * _BATCH)()
        self._kinds = (ctypes.c_int * _BATCH)()
        self._datas = (ctypes.POINTER(ctypes.c_ubyte) * _BATCH)()
        self._lens = (ctypes.c_uint32 * _BATCH)()

    def listen(self, path: str):
        if self._lib.rpcx_listen(self._p, path.encode()) != 0:
            raise OSError(f"rpcx: cannot listen on {path}")

    def listen_tcp(self, host: str, port: int = 0) -> int:
        """Bind a TCP listener on the same reactor; returns the bound
        port (``port=0`` = ephemeral). Accepted connections speak the
        identical frame format as the unix path."""
        bound = self._lib.rpcx_listen_tcp(self._p, host.encode(), port)
        if bound < 0:
            raise OSError(f"rpcx: cannot listen on {host}:{port}")
        return bound

    def dial(self, address: str) -> int:
        """Dial either a unix socket path or a ``host:port`` TCP
        endpoint (``unix:`` / ``tcp:`` prefixes accepted)."""
        if address.startswith("unix:"):
            address = address[5:]
        elif address.startswith("tcp:"):
            address = address[4:]
        if is_tcp_address(address):
            host, _, port = address.rpartition(":")
            return self.dial_tcp(host, int(port))
        cid = self._lib.rpcx_dial(self._p, address.encode())
        if cid < 0:
            raise ConnectionError(f"rpcx: cannot dial {address}")
        return cid

    def dial_tcp(self, host: str, port: int) -> int:
        cid = self._lib.rpcx_dial_tcp(self._p, host.encode(), int(port))
        if cid < 0:
            raise ConnectionError(f"rpcx: cannot dial {host}:{port}")
        return cid

    def next_batch(self, timeout_ms: int = 200
                   ) -> Optional[List[Tuple[int, int, Optional[bytes]]]]:
        """Returns [(cid, kind, body)] — body is None for KIND_CLOSED —
        an empty list on timeout, or None after shutdown()."""
        n = self._lib.rpcx_next_batch(
            self._p, self._cids, self._kinds, self._datas, self._lens,
            _BATCH, timeout_ms)
        if n < 0:
            return None
        out = []
        for i in range(n):
            kind = self._kinds[i]
            body = None
            if kind == KIND_FRAME:
                body = ctypes.string_at(self._datas[i], self._lens[i])
                self._lib.rpcx_free(self._datas[i])
            out.append((self._cids[i], kind, body))
        return out

    def send(self, cid: int, body: bytes) -> bool:
        """Frame + write ``body`` (msgpack bytes). False = conn dead."""
        return self._lib.rpcx_send(self._p, cid, body, len(body)) == 0

    def close_conn(self, cid: int):
        self._lib.rpcx_close_conn(self._p, cid)

    def wake(self):
        """Bounce the thread inside next_batch out of its epoll wait."""
        self._lib.rpcx_wake(self._p)

    def shutdown(self):
        """Wake the lane thread out of next_batch permanently."""
        self._lib.rpcx_shutdown(self._p)

    def destroy(self):
        """Free the native pump. Only after the lane thread exited."""
        with self._destroy_lock:
            if self._destroyed:
                return
            self._destroyed = True
        self._lib.rpcx_destroy(self._p)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 6)()
        self._lib.rpcx_stats(self._p, out)
        return {"frames_in": out[0], "frames_out": out[1],
                "bytes_in": out[2], "bytes_out": out[3],
                "read_calls": out[4], "write_calls": out[5]}
