"""Custom reducer plumbing for ObjectRefs/ActorHandles.

Reference semantics: ObjectRefs and ActorHandles have custom reducers that
carry owner addresses; when a ref is deserialized in another process that
process registers as a *borrower* with the owner (SURVEY.md §8.1, reference:
core_worker/reference_count.h AddBorrowedObject). Serialization of a value
collects every contained ref so the envelope can list them (the owner then
adds submitted-task/borrower references before the value leaves the process).
"""

from __future__ import annotations

import contextlib
import threading
from typing import List

_tls = threading.local()


@contextlib.contextmanager
def collecting_refs(sink: List):
    """While active, ObjectRef reducers append (hex_id, owner_addr) to sink."""
    prev = getattr(_tls, "sink", None)
    _tls.sink = sink
    try:
        yield sink
    finally:
        _tls.sink = prev


def record_ref(ref_info):
    sink = getattr(_tls, "sink", None)
    if sink is not None:
        sink.append(ref_info)
