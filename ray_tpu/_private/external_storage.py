"""Pluggable external storage for object spilling.

Reference analogue: python/ray/_private/external_storage.py —
ExternalStorage ABC (:72), FileSystemStorage (:233), the smart_open
S3/URI backend (:293), and ExternalStorageRayStorageImpl (:368) riding
the cluster storage root. The raylet spills primary copies through one
of these; which one comes from SystemConfig.object_spilling_config
(JSON, the reference's `object_spilling_config` system-config knob).

URIs are self-describing ("file://...", "mem://...", "s3://..."), so a
restarted raylet can restore objects spilled by its predecessor from
the recorded URI alone.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple


class ExternalStorage:
    """Spill target. Implementations must be safe for concurrent calls
    from the raylet's executor threads."""

    def spill(self, key: str, data: bytes) -> str:
        """Persist ``data`` under ``key``; returns a restore URI."""
        raise NotImplementedError

    def restore(self, uri: str) -> bytes:
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """Local-disk spilling (the default; reference :233)."""

    def __init__(self, directory: str):
        self.dir = directory

    def spill(self, key: str, data: bytes) -> str:
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return f"file://{path}"

    def restore(self, uri: str) -> bytes:
        with open(uri[len("file://"):], "rb") as f:
            return f.read()

    def delete(self, uri: str) -> None:
        try:
            os.unlink(uri[len("file://"):])
        except OSError:
            pass


class MemoryStorage(ExternalStorage):
    """In-process dict-backed storage — the test double for the plugin
    seam (URIs survive only as long as the raylet process)."""

    def __init__(self):
        self._blobs: Dict[str, bytes] = {}

    def spill(self, key: str, data: bytes) -> str:
        self._blobs[key] = bytes(data)
        return f"mem://{key}"

    def restore(self, uri: str) -> bytes:
        return self._blobs[uri[len("mem://"):]]

    def delete(self, uri: str) -> None:
        self._blobs.pop(uri[len("mem://"):], None)


class SmartOpenStorage(ExternalStorage):
    """S3/GCS/arbitrary-URI spilling via smart_open (reference :293).
    Gated: constructing it without the library raises ImportError with
    the pip hint, exactly like the reference."""

    def __init__(self, uri_prefix: str):
        try:
            from smart_open import open as _so_open  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "smart_open is required for URI spilling: "
                "pip install smart_open[s3]") from e
        self._open = _so_open
        self.prefix = uri_prefix.rstrip("/")

    def spill(self, key: str, data: bytes) -> str:
        uri = f"{self.prefix}/{key}"
        with self._open(uri, "wb") as f:
            f.write(data)
        return uri

    def restore(self, uri: str) -> bytes:
        with self._open(uri, "rb") as f:
            return f.read()

    def delete(self, uri: str) -> None:
        # S3 deletes need boto3; best-effort like the reference
        try:
            import boto3  # noqa: F401
            from urllib.parse import urlparse
            p = urlparse(uri)
            boto3.client("s3").delete_object(Bucket=p.netloc,
                                             Key=p.path.lstrip("/"))
        except Exception:
            pass


class RayStorageImpl(ExternalStorage):
    """Spill into the cluster storage root configured by
    ``ray_tpu.init(storage=...)`` (reference:
    ExternalStorageRayStorageImpl :368) — one namespace for workflow
    state, checkpoints, AND spilled objects."""

    def __init__(self, storage_root: str, node_id: str):
        self.inner = FileSystemStorage(
            os.path.join(storage_root, "spilled_objects", node_id[:12]))

    def spill(self, key: str, data: bytes) -> str:
        return self.inner.spill(key, data)

    def restore(self, uri: str) -> bytes:
        return self.inner.restore(uri)

    def delete(self, uri: str) -> None:
        self.inner.delete(uri)


def storage_from_config(spec: Any, default_dir: str,
                        node_id: str = "",
                        storage_root: Optional[str] = None
                        ) -> ExternalStorage:
    """Build the spill backend from the object_spilling_config knob:
    a JSON string or dict {"type": ..., "params": {...}}."""
    if not spec:
        return FileSystemStorage(default_dir)
    if isinstance(spec, str):
        spec = json.loads(spec)
    typ = spec.get("type", "filesystem")
    params = spec.get("params") or {}
    if typ == "filesystem":
        return FileSystemStorage(params.get("directory_path",
                                            default_dir))
    if typ == "memory":
        return MemoryStorage()
    if typ == "smart_open":
        return SmartOpenStorage(params["uri_prefix"])
    if typ == "ray_storage":
        root = params.get("root") or storage_root
        if not root:
            raise ValueError("ray_storage spilling needs a cluster "
                             "storage root (ray_tpu.init(storage=...))")
        return RayStorageImpl(root, node_id)
    raise ValueError(f"unknown object spilling type {typ!r}")
