"""Cluster-wide storage root (reference: python/ray/_private/storage.py).

``ray_tpu.init(storage="/mnt/shared")`` pins the root; on a running
cluster it is published through the GCS KV so every worker resolves the
same path. ``get_filesystem()`` hands back (root, exists-helpers) for
components needing durable shared storage (workflows default here).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_KV_KEY = "@storage/root"


def _publish(root: str):
    try:
        from ray_tpu._private import worker as wm
        w = wm._global_worker
        if w is not None and w.connected:
            w.call_sync(w.gcs, "kv_put",
                        {"key": _KV_KEY, "value": root.encode(),
                         "overwrite": True}, timeout=10)
    except Exception as e:
        # remote workers resolve the root from the GCS KV: a dropped
        # publish means they silently fall back to local defaults
        logger.warning("failed to publish storage root to the GCS "
                       "(remote workers won't see it): %s", e)


def _strip_scheme(root: str) -> str:
    return root[len("file://"):] if root.startswith("file://") else root


def get_storage_root() -> Optional[str]:
    env = os.environ.get("RTPU_STORAGE")
    if env:
        return _strip_scheme(env)
    try:
        from ray_tpu._private import worker as wm
        w = wm._global_worker
        if w is not None and w.connected:
            r = w.call_sync(w.gcs, "kv_get", {"key": _KV_KEY},
                            timeout=10)
            v = r.get("value")
            if v:
                return _strip_scheme(
                    v.decode() if isinstance(v, bytes) else str(v))
    except Exception:
        pass
    return None


def storage_path(*parts: str) -> str:
    """Join under the configured root (creates directories)."""
    root = get_storage_root()
    if root is None:
        raise RuntimeError(
            "no storage configured — pass ray_tpu.init(storage=...)")
    p = os.path.join(root, *parts)
    os.makedirs(os.path.dirname(p) or p, exist_ok=True)
    return p
