"""Versioned wire schema for the control-plane RPC surface.

Role-equivalent to the reference's ``src/ray/protobuf/`` (21 ``.proto``
files: gcs_service.proto 43 rpcs, node_manager.proto 23,
core_worker.proto 20, …). The transport here is msgpack, so the schema
is declarative Python instead of protoc codegen — but it serves the
same two contracts:

1. **Versioning.** ``PROTOCOL_VERSION`` plus a content hash of the
   schema table ride the ``__hello__`` negotiation (protocol.py). A
   peer from a different major version is rejected at connect time
   instead of failing obscurely mid-RPC.
2. **Message shape.** Every field of the core RPC payloads is declared
   with a type and requiredness. ``validate()`` enforces the table;
   servers run it on every inbound request when
   ``RTPU_VALIDATE_WIRE=1`` (tests enable this so schema drift is
   caught the moment a handler grows an undeclared field).

Unknown fields are ALLOWED (forward compatibility — new minor versions
add fields; old peers ignore them), exactly the proto3 rule.
"""

from __future__ import annotations

import hashlib
import numbers
import os
from typing import Any, Dict, List, Optional, Tuple

# Major bumps = incompatible framing/semantics; minor bumps = added
# methods/fields (compatible both ways).
# 1.1: leases (lease_worker/release_lease/revoke_lease/leased_task),
#      coalesced dispatch statuses, task_stats, profile_worker(s),
#      worker-lifecycle methods joined the schema table.
#      task_dispatch_status_batch is gated on the peer having negotiated
#      >= 1.1 via __hello__; legacy peers get per-task statuses.
# 1.2: preemption drain (preempt/preempt_node/node_draining/
#      node_drained/preemption_notice), release_lease.inflight
#      revoke-drain ack, per-chunk crc on pull_object replies.
# 1.3: kv_get_prefix (bulk journal recovery reads — serve control-plane
#      HA), drain_deadline_unix in get_nodes replies.
# 1.4: state engine — task_events batches, list_tasks/list_objects/
#      summarize/summarize_tasks GCS methods, raylet-side list_objects,
#      cursor pagination fields (paged/limit/continuation_token/filters)
#      on every list_* method (legacy non-paged replies retained).
# 1.5: compiled-DAG channels — dag_channel_open/dag_channel_close on
#      workers, dag_register/dag_unregister on raylets, dag_stage_error/
#      dag_peer_down owner notifies, and the dag_exec/dag_result frames
#      that ride the dedicated channel sockets. Channel opens are gated
#      on the peer having negotiated >= 1.5 via __hello__ (a legacy peer
#      degrades the whole graph to dynamic dispatch — docs/
#      COMPILED_DAGS.md).
# 1.6: distributed tracing — trace_spans batches + get_trace/list_traces
#      GCS methods, trace_ctx on actor_call, the optional "tc" trace
#      context on dag_exec/dag_result channel frames (only sent when
#      every stage peer negotiated >= 1.6 via __hello__ — a legacy peer
#      runs the graph untraced, never broken), trace_table_max on
#      configure_state — docs/TRACING.md.
# 1.7: native direct-execution lane — optional direct_address on
#      worker_register and lease_worker replies (the worker's second
#      listening socket served by the native frame pump; leased tasks
#      pushed there run recv→decode→execute→reply on one thread). All
#      frames on the direct socket are standard 1.x frames; an owner or
#      worker without the native library simply never sees/sends the
#      field and everything rides the asyncio path —
#      docs/WIRE_PROTOCOL.md "Implementations".
# 1.8: netx cross-node transport plane — endpoints become real
#      host:port pairs: netx_address on register_node/get_nodes/
#      get_object_locations (the raylet's transfer server),
#      direct_tcp_address on worker_register/lease_worker/
#      create_actor_worker (the direct lane's TCP twin),
#      channel_tcp_address on dag_channel_open replies, and the px_*
#      object-transfer methods (px_get/px_pull + px_chunk/px_ack
#      notifies) served by the netx transfer server. Same-host peers
#      keep dialing the unix endpoints; a pre-1.8 peer never sees the
#      new fields and rides the asyncio pull path —
#      docs/WIRE_PROTOCOL.md "1.8: host:port endpoint advertisement".
PROTOCOL_VERSION = (1, 8)

# Methods introduced after 1.0 (method -> first schema minor carrying
# it). Callers gate on the peer's negotiated minor from ``__hello__``
# before sending these to a long-lived connection; an unknown method
# on an old peer is an RpcError mid-flight instead of a clean
# downgrade. Kept next to SCHEMAS so a new method can't land without a
# version row (the conformance vectors iterate this).
METHOD_VERSIONS: Dict[str, Tuple[int, int]] = {
    "lease_worker": (1, 1), "release_lease": (1, 1),
    "revoke_lease": (1, 1), "leased_task": (1, 1),
    "task_dispatch_status_batch": (1, 1), "task_stats": (1, 1),
    "profile_worker": (1, 1), "profile_workers": (1, 1),
    "preempt": (1, 2), "preempt_node": (1, 2),
    "node_draining": (1, 2), "node_drained": (1, 2),
    "preemption_notice": (1, 2),
    "kv_get_prefix": (1, 3),
    "task_events": (1, 4), "list_tasks": (1, 4),
    "list_objects": (1, 4), "summarize": (1, 4),
    "summarize_tasks": (1, 4), "configure_state": (1, 4),
    "dag_channel_open": (1, 5), "dag_channel_close": (1, 5),
    "dag_register": (1, 5), "dag_unregister": (1, 5),
    "dag_stage_error": (1, 5), "dag_peer_down": (1, 5),
    "dag_exec": (1, 5), "dag_result": (1, 5),
    "trace_spans": (1, 6), "get_trace": (1, 6), "list_traces": (1, 6),
    "px_get": (1, 8), "px_pull": (1, 8),
    "px_chunk": (1, 8), "px_ack": (1, 8),
}

# Fields added to PRE-EXISTING methods after 1.0 — the compat-critical
# map: a peer that negotiated an older minor never sends these, so
# reading one takes either an absence-tolerant ``.get()`` or a
# negotiated-version guard (rtpulint RTPU006 enforces exactly this,
# keyed off this table). (method, field) -> minor introduced. Fields
# born with their method (METHOD_VERSIONS above) need no row — method
# existence already gates them.
FIELD_VERSIONS: Dict[Tuple[str, str], Tuple[int, int]] = {
    # 1.2: revoke-drain ack + per-chunk pull integrity (reply side)
    ("release_lease", "inflight"): (1, 2),
    ("pull_object", "crc"): (1, 2),
    # 1.3: drain deadlines surfaced in node-table reads (reply side)
    ("get_nodes", "drain_deadline_unix"): (1, 3),
    # 1.6: trace contexts ride task/actor/channel frames
    ("submit_task", "trace_ctx"): (1, 6),
    ("actor_call", "trace_ctx"): (1, 6),
    ("dag_exec", "tc"): (1, 6),
    ("dag_result", "tc"): (1, 6),
    ("configure_state", "trace_table_max"): (1, 6),
    # 1.7: the native direct-call lane address (worker_register
    # request + lease_worker reply)
    ("worker_register", "direct_address"): (1, 7),
    ("lease_worker", "direct_address"): (1, 7),
    # 1.8: netx endpoint advertisement (host:port twins of the unix
    # endpoints; '' or absent = unix-only peer)
    ("register_node", "netx_address"): (1, 8),
    ("get_nodes", "netx_address"): (1, 8),
    ("get_object_locations", "netx_address"): (1, 8),
    ("worker_register", "direct_tcp_address"): (1, 8),
    ("lease_worker", "direct_tcp_address"): (1, 8),
    ("create_actor_worker", "direct_address"): (1, 8),
    ("create_actor_worker", "direct_tcp_address"): (1, 8),
    ("dag_channel_open", "channel_tcp_address"): (1, 8),
}

_str = str
_num = numbers.Number
_int = numbers.Integral
_bool = (bool, numbers.Integral)
_dict = dict
_list = (list, tuple)
_bytes = (bytes, bytearray, memoryview)
_any = object

# method -> {field: (type, required)}. Covers the compat-critical
# surface: node lifecycle + sync stream, scheduling, task/actor
# submission, the object plane, KV, and pubsub. Handler-local or
# purely-internal methods may be absent — validate() passes unknown
# methods through (the proto3 unknown-message stance).
SCHEMAS: Dict[str, Dict[str, Tuple[Any, bool]]] = {
    # ---- GCS: node lifecycle + versioned sync (ray_syncer.proto role)
    "register_node": {
        "node_id": (_str, True),
        "raylet_address": (_str, True),
        # 1.8: the node's netx transfer server ("" = asyncio-only)
        "netx_address": (_str, False),
        "object_store_path": (_str, True),
        "resources": (_dict, True),
        "labels": (_dict, False),
        "tpu": (_dict, False),
        "hostname": (_str, False),
        "is_head": (_bool, False),
        "objects": (_list, False),
        "sync_epoch": (_num, False),
        "sync_version": (_int, False),
    },
    "resource_report": {
        "node_id": (_str, True),
        "available": (_dict, True),
        "total": (_dict, False),
        "sync_epoch": (_num, False),
        "sync_version": (_int, False),
        "known_view": (_int, False),
    },
    "drain_node": {"node_id": (_str, True)},
    # preemption drain (1.2): notice delivery + node-table state
    "preempt_node": {"node_id": (_str, True), "grace_s": (_num, False),
                     "reason": (_str, False)},
    "preempt": {"grace_s": (_num, False), "reason": (_str, False)},
    "node_draining": {"node_id": (_str, True), "grace_s": (_num, False),
                      "deadline_unix": (_num, False),
                      "reason": (_str, False)},
    "node_drained": {"node_id": (_str, True), "reason": (_str, False)},
    "preemption_notice": {"deadline_unix": (_num, False),
                          "grace_s": (_num, False)},
    "get_node_stats": {"node_id": (_str, False)},
    "profile_stacks": {"node_id": (_str, False),
                       "worker_id": (_str, False)},
    # ---- GCS: scheduling (gcs_service.proto scheduling rpcs role)
    "schedule": {
        "demand": (_dict, True),
        "scheduling": (_dict, False),
        "deps": (_list, False),
    },
    # ---- GCS: actors (gcs_service.proto ActorInfoGcsService role)
    "register_actor": {
        "actor_id": (_str, True),
        "class_name": (_str, False),
        "demand": (_dict, False),
        "name": (_str, False),
        "namespace": (_str, False),
        "lifetime": (_str, False),
        "max_restarts": (_int, False),
        "owner": (_str, False),
        "runtime_env": (_dict, False),
        "scheduling": (_dict, False),
        "max_concurrency": (_int, False),
        "concurrency_groups": (_dict, False),
    },
    "get_actor": {"actor_id": (_str, True)},
    "wait_actor_alive": {"actor_id": (_str, True),
                         "timeout": (_num, False)},
    "kill_actor": {"actor_id": (_str, True),
                   "no_restart": (_bool, False)},
    # ---- GCS: placement groups (node_manager.proto 2-phase rpcs role)
    "create_placement_group": {
        "pg_id": (_str, True),
        "bundles": (_list, True),
        "strategy": (_str, False),
        "name": (_str, False),
        "owner": (_str, False),
    },
    "remove_placement_group": {"pg_id": (_str, True)},
    # ---- GCS: KV + pubsub (gcs_kv_manager / pubsub.proto role)
    "kv_put": {"key": (_any, True), "value": (_any, True),
               "overwrite": (_bool, False)},
    "kv_get": {"key": (_any, True)},
    "kv_get_prefix": {"prefix": (_any, False)},
    "kv_del": {"key": (_any, True)},
    "kv_keys": {"prefix": (_any, False)},
    "kv_exists": {"key": (_any, True)},
    "subscribe": {"channels": (_list, True)},
    "unsubscribe": {"channels": (_list, True)},
    "publish": {"channel": (_str, True), "message": (_any, True)},
    # ---- GCS: object directory (object_manager.proto role)
    "add_object_location": {"object_id": (_str, True),
                            "node_id": (_str, True),
                            "owner": (_str, False)},
    "remove_object_location": {"object_id": (_str, True),
                               "node_id": (_str, True)},
    "get_object_locations": {"object_id": (_str, True)},
    # ---- raylet: task submission (node_manager.proto role)
    "submit_task": {
        "task_id": (_str, True),
        "fn_name": (_str, False),
        "args": (_bytes, False),
        "demand": (_dict, False),
        "num_returns": (_int, False),
        "max_retries": (_int, False),
        "retry_exceptions": (_bool, False),
        "runtime_env": (_dict, False),
        "scheduling": (_dict, False),
        "plasma_deps": (_list, False),
        "arg_refs": (_list, False),
        "spilled_from": (_str, False),
        "owner": (_str, False),
        "job_id": (_str, False),
        "trace_ctx": (_dict, False),
    },
    "submit_task_batch": {"specs": (_list, True)},
    "task_dispatch_status_batch": {"statuses": (_list, True)},
    "task_dispatch_status": {"task_id": (_str, True)},
    "task_done": {"task_id": (_str, True)},
    "lease_worker": {"resources": (_dict, False)},
    # inflight (1.2): 0 acks a revoke-drain — the raylet defers
    # re-idling the leased worker until this arrives
    "release_lease": {"lease_id": (_str, True),
                      "inflight": (_int, False)},
    "revoke_lease": {"lease_id": (_str, True)},
    "task_stats": {"executed": (_int, True)},
    "leased_task": {"spec": (_dict, True)},
    "cancel_task": {"task_id": (_str, True)},
    "request_spill": {"bytes_needed": (_int, False)},
    # ---- raylet: object plane (object_manager.proto role)
    "pull_object": {"object_id": (_str, True), "offset": (_int, True),
                    "length": (_int, True)},
    # ---- netx transfer plane (1.8): chunk-pipelined object pulls on
    # the raylet's dedicated transfer server (push_manager.cc role,
    # served by the native pump — _private/netx/server.py)
    "px_get": {"object_id": (_str, True)},
    "px_pull": {"object_id": (_str, True), "offset": (_int, True),
                "stream": (_int, True),
                # the puller's advertised host: keys the one-direction
                # net.partition chaos site on server→client chunk sends
                "from_host": (_str, False)},
    # notify: one windowed chunk of an object stream (server → puller)
    "px_chunk": {"stream": (_int, True), "offset": (_int, True),
                 "data": (_bytes, True), "crc": (_int, False),
                 "total_size": (_int, False), "last": (_bool, False)},
    # notify: puller's contiguous high-water ack (-1 = cancel stream)
    "px_ack": {"stream": (_int, True), "got": (_int, True)},
    "receive_push": {"object_id": (_str, True), "offset": (_int, True),
                     "total_size": (_int, True), "data": (_bytes, True)},
    "fetch_object": {"object_id": (_str, True)},
    "pin_object": {"object_id": (_str, True), "owner": (_str, False)},
    "contains_object": {"object_id": (_str, True)},
    "free_objects": {"object_ids": (_list, True)},
    # ---- worker: direct actor transport (core_worker.proto role)
    "actor_call": {
        "task_id": (_str, True),
        "method": (_str, True),
        "args": (_bytes, False),
        "seq": (_int, False),
        "processed_up_to": (_int, False),
        "caller": (_str, False),
        # 1.6: the caller's span context — tasks submitted from inside
        # the method parent under the call instead of a fresh root
        "trace_ctx": (_dict, False),
    },
    # ---- compiled-DAG channels (1.5; docs/COMPILED_DAGS.md). The
    # control-plane trio (open/close/register) rides the normal RPC
    # surface; dag_exec/dag_result are declared here for the conformance
    # vectors but flow over the dedicated channel sockets.
    "dag_channel_open": {
        "dag_id": (_str, True),
        "stage_id": (_int, True),
        "method": (_str, True),
        "args_tpl": (_list, True),
        "kwargs_tpl": (_dict, False),
        "downstream": (_list, True),
        "owner_address": (_str, True),
        "ring": (_dict, False),
    },
    "dag_channel_close": {"dag_id": (_str, True),
                          "stage_id": (_int, False)},
    "dag_register": {"dag_id": (_str, True),
                     "owner_address": (_str, False)},
    "dag_unregister": {"dag_id": (_str, True)},
    "dag_stage_error": {"dag_id": (_str, True), "stage_id": (_int, False),
                        "seq": (_int, False), "reason": (_str, False)},
    "dag_peer_down": {"dag_id": (_str, True),
                      "worker_id": (_str, False)},
    "dag_exec": {"d": (_str, True), "t": (_int, True), "s": (_int, True),
                 "b": (_bytes, False), "o": (_str, False),
                 "n": (_int, False),
                 # 1.6: {"trace_id","span_id"} — stages record hop
                 # spans chained under it; absent on pre-1.6 graphs
                 "tc": (_dict, False)},
    "dag_result": {"d": (_str, True), "s": (_int, True), "i": (_int, True),
                   "ae": (_bool, False), "b": (_bytes, False),
                   "o": (_str, False), "n": (_int, False),
                   "tc": (_dict, False)},
    # ---- worker lifecycle (the second-language worker surface —
    # docs/WIRE_PROTOCOL.md declares this table normative for it)
    "worker_register": {"worker_id": (_str, True),
                        "address": (_str, True),
                        # 1.7: native direct-call lane socket ("" when
                        # the pump is disabled)
                        "direct_address": (_str, False),
                        # 1.8: the lane's host:port twin for off-box
                        # owners ("" when netx is off)
                        "direct_tcp_address": (_str, False)},
    "push_task": {"spec": (_dict, True), "tpu_chips": (_list, False)},
    "task_result": {"task_id": (_str, True), "returns": (_list, True),
                    "app_error": (_bool, False)},
    "ping": {},
    "exit_worker": {},
    "dump_stacks": {},
    "node_stats": {},
    "dump_worker_stacks": {"worker_id": (_str, False)},
    "profile_worker": {"duration_s": (_num, False),
                       "interval_s": (_num, False)},
    "profile_workers": {"worker_id": (_str, False),
                        "duration_s": (_num, False),
                        "interval_s": (_num, False)},
    # ---- state engine (gcs_task_manager / state aggregator role).
    # The pagination trio (paged/limit/continuation_token/filters) also
    # rides the legacy list_* methods as unknown-but-allowed fields.
    "task_events": {"events": (_list, True), "dropped": (_int, False)},
    "list_tasks": {"paged": (_bool, False), "limit": (_int, False),
                   "continuation_token": (_any, False),
                   "filters": (_dict, False)},
    "list_objects": {"paged": (_bool, False), "limit": (_int, False),
                     "continuation_token": (_any, False),
                     "filters": (_dict, False),
                     "node_id": (_any, False)},
    "summarize": {},
    "summarize_tasks": {},
    "configure_state": {"task_table_max": (_int, False),
                        "trace_table_max": (_int, False)},
    # ---- distributed tracing (1.6; docs/TRACING.md)
    "trace_spans": {"spans": (_list, True), "dropped": (_int, False)},
    "get_trace": {"trace_id": (_str, True)},
    "list_traces": {"paged": (_bool, False), "limit": (_int, False),
                    "continuation_token": (_any, False),
                    "filters": (_dict, False)},
}


def schema_hash() -> str:
    """Content hash of the schema table (drift detector for hello)."""
    items = []
    for method in sorted(SCHEMAS):
        for field in sorted(SCHEMAS[method]):
            t, req = SCHEMAS[method][field]
            items.append(f"{method}.{field}:{t}:{req}")
    return hashlib.sha1("|".join(items).encode()).hexdigest()[:16]


def hello_payload() -> Dict[str, Any]:
    return {"protocol_version": list(PROTOCOL_VERSION),
            "schema_hash": schema_hash()}


def check_hello(peer: Dict[str, Any]) -> Optional[str]:
    """None if compatible, else a reason string. Major must match;
    minor skew and schema-hash skew are compatible (unknown fields are
    ignored) but the hash is surfaced for diagnostics."""
    ver = peer.get("protocol_version")
    if not isinstance(ver, (list, tuple)) or len(ver) != 2:
        return f"malformed protocol_version: {ver!r}"
    if int(ver[0]) != PROTOCOL_VERSION[0]:
        return (f"incompatible protocol major version {ver[0]} "
                f"(ours: {PROTOCOL_VERSION[0]})")
    return None


def validate(method: str, payload: Any) -> List[str]:
    """Field errors for ``payload`` against ``method``'s schema;
    empty list = valid (or method not in the table)."""
    spec = SCHEMAS.get(method)
    if spec is None:
        return []
    if payload is None:
        payload = {}
    if not isinstance(payload, dict):
        return [f"{method}: payload must be a map, got "
                f"{type(payload).__name__}"]
    errors = []
    for field, (ftype, required) in spec.items():
        if field not in payload:
            if required:
                errors.append(f"{method}.{field}: required field missing")
            continue
        value = payload[field]
        if value is None and not required:
            continue
        if ftype is _any:
            continue
        if not isinstance(value, ftype):
            errors.append(
                f"{method}.{field}: expected "
                f"{getattr(ftype, '__name__', ftype)}, got "
                f"{type(value).__name__}")
    return errors


def validation_enabled() -> bool:
    return os.environ.get("RTPU_VALIDATE_WIRE", "") not in ("", "0")
